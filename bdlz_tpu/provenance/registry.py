"""Content-addressed emulator-artifact registry (docs/provenance.md).

The serving tier's rollout story (``serve/rollout.py``) needs a way to
move artifact builds between hosts that is as tamper-evident as the
artifacts themselves: a build host PUBLISHES an artifact into the shared
store under its content hash, and every serving host STAGES it by hash —
the fetch re-verifies the full PR-3 validation chain (schema version,
content hash, finite/positive tables) plus that the entry actually IS
the requested hash, so a registry entry can never impersonate another
build.

Entries are directories ``<root>/emulator_artifact/<hash>/`` holding the
standard ``artifact.npz`` + ``manifest.json`` pair (written by
``emulator.artifact.save_artifact``).  Publication is atomic: the pair
is written into a temp directory in the store root and renamed into
place; a loser of a publish race simply discards its temp copy — the
content under a hash is identical by construction.  A corrupt entry is
deleted on fetch (one re-publish, never a poisoned stage).
"""
from __future__ import annotations

import os
import shutil
import sys
import tempfile

from bdlz_tpu.provenance.store import Store

ARTIFACT_KIND = "emulator_artifact"


def publish_artifact(store: Store, artifact) -> str:
    """Publish an :class:`~bdlz_tpu.emulator.artifact.EmulatorArtifact`,
    a seam-split :class:`~bdlz_tpu.emulator.multidomain.MultiDomainArtifact`
    bundle, or an artifact/bundle directory path into ``store``; returns
    the content hash it is addressable by (the COMPOSITE hash for a
    bundle — the whole bundle moves as one unit)."""
    from bdlz_tpu.emulator.artifact import EmulatorArtifact, save_artifact
    from bdlz_tpu.emulator.multidomain import (
        MultiDomainArtifact,
        load_any_artifact,
        save_multidomain_artifact,
    )

    if not isinstance(artifact, (EmulatorArtifact, MultiDomainArtifact)):
        artifact = load_any_artifact(str(artifact))
    content_hash = artifact.content_hash
    dest = os.path.join(store.root, ARTIFACT_KIND, content_hash)
    os.makedirs(os.path.join(store.root, ARTIFACT_KIND), mode=0o700,
                exist_ok=True)
    if os.path.isdir(dest):
        store.stats.hits += 1
        return content_hash  # same hash = same bytes; nothing to do
    tmp = tempfile.mkdtemp(dir=store.root, suffix=".tmp")
    try:
        if isinstance(artifact, MultiDomainArtifact):
            save_multidomain_artifact(tmp, artifact)
        else:
            save_artifact(tmp, artifact)
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            # benign ONLY if a concurrent publisher won the rename
            # (identical content under the same hash); any other rename
            # failure must surface — returning a hash that was never
            # published would strand every later fetch
            if not os.path.isdir(dest):
                raise
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    store.stats.writes += 1
    return content_hash


#: Process-wide fetch call counter — the key injected ``registry_fetch``
#: faults fire on (deterministic in a single process; reset in tests via
#: :func:`reset_fetch_counter`).
_fetch_calls = 0


def reset_fetch_counter() -> None:
    global _fetch_calls
    _fetch_calls = 0


def _inject_fetch_fault(fault_plan, key: int, path: str) -> None:
    """Apply an armed ``registry_fetch`` fault to the entry BEFORE the
    load: ``torn`` truncates its payload (the corrupt-entry eviction
    path must detect-and-delete), ``corrupt`` flips one byte (the
    content-hash verification must refuse it).  The damaged file is the
    entry's ``artifact.npz`` when present, its ``manifest.json``
    otherwise (a multi-domain bundle's top level)."""
    for name in ("artifact.npz", "manifest.json"):
        target = os.path.join(path, name)
        if os.path.isfile(target):
            fault_plan.corrupt_file("registry_fetch", key, target)
            fault_plan.corrupt_bytes("registry_fetch", key, target)
            return


def fetch_artifact(store: Store, content_hash: str, fault_plan=None):
    """Load + fully validate the published artifact ``content_hash``
    (kind-dispatched: a single artifact or a multi-domain bundle).

    Raises :class:`~bdlz_tpu.emulator.artifact.EmulatorArtifactError`
    when the entry is absent, fails any load-time validation, or its
    verified hash is not the requested one (an impersonating or
    renamed entry); a corrupt entry is deleted first, so the next
    publish starts clean.  ``fault_plan`` (site ``registry_fetch``,
    keyed by the per-process fetch call counter) exercises exactly
    those refusal paths deterministically — see bdlz_tpu/faults.py."""
    from bdlz_tpu.emulator.artifact import EmulatorArtifactError
    from bdlz_tpu.emulator.multidomain import load_any_artifact

    global _fetch_calls
    fetch_key = _fetch_calls
    _fetch_calls += 1
    path = os.path.join(store.root, ARTIFACT_KIND, str(content_hash))
    if fault_plan is not None and os.path.isdir(path):
        _inject_fetch_fault(fault_plan, fetch_key, path)
    if not os.path.isdir(path):
        store.stats.misses += 1
        raise EmulatorArtifactError(
            f"no published emulator artifact {content_hash!r} in store "
            f"{store.root}"
        )
    try:
        artifact = load_any_artifact(path)
    except EmulatorArtifactError:
        print(
            f"[registry] published artifact entry {path} failed validation; "
            "deleting the corrupt entry",
            file=sys.stderr,
        )
        shutil.rmtree(path, ignore_errors=True)
        store.stats.dropped_corrupt += 1
        raise
    if artifact.content_hash != str(content_hash):
        raise EmulatorArtifactError(
            f"registry entry {path} verifies as {artifact.content_hash!r}, "
            f"not the requested {content_hash!r}: refusing the impersonating "
            "entry"
        )
    store.stats.hits += 1
    return artifact


# ---- lease records (the elastic scheduler's claim plane) ----------------
#
# One small JSON record per (job, chunk) under ``lease/`` in the shared
# store.  The *policy* (TTLs, steal-on-expiry, distinct-failure
# quarantine) lives in ``parallel/scheduler.py``; this layer provides
# only the storage primitives, with the one property the policy cannot
# build for itself: an EXCLUSIVE create (``os.link`` of a temp file —
# atomic on POSIX, fails with EEXIST when another worker claimed first).
# Overwrites (heartbeat, steal, complete) go through the store's atomic
# durable JSON write; a lost overwrite race is safe because the commit
# protocol (first ``put_npz`` wins, later commits verify bitwise) — not
# the lease record — is what makes results correct.  A torn/corrupt
# record reads as None (``Store.get_json`` drops it), which the policy
# treats as a free chunk: the worst case is a double-computation the
# commit protocol resolves.

LEASE_KIND = "lease"


def lease_entry_name(job: str, chunk: int) -> str:
    """Store entry name of the lease record for ``(job, chunk)``."""
    return f"{LEASE_KIND}/{job}_{int(chunk):05d}.json"


def read_lease(store: Store, job: str, chunk: int):
    """The lease record dict, or None when absent/torn (torn records are
    evicted by the store and re-claimable — see module comment)."""
    return store.get_json(lease_entry_name(job, chunk))


def write_lease(store: Store, job: str, chunk: int, record) -> str:
    """Atomically overwrite the lease record (heartbeat/steal/complete)."""
    return store.put_json(lease_entry_name(job, chunk), record)


def create_lease(store: Store, job: str, chunk: int, record) -> bool:
    """Atomically create the lease record IFF absent; True when this
    caller won the claim.  mkstemp + ``os.link`` (not ``os.replace``,
    which would silently overwrite a racing winner): the link fails with
    EEXIST when any other worker already holds the name."""
    import json as jsonlib
    import tempfile

    path = store.path_for(lease_entry_name(job, chunk))
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            jsonlib.dump(record, f)
        try:
            os.link(tmp, path)
        except FileExistsError:
            return False
        store.stats.writes += 1
        return True
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
