"""Content-addressed emulator-artifact registry (docs/provenance.md).

The serving tier's rollout story (``serve/rollout.py``) needs a way to
move artifact builds between hosts that is as tamper-evident as the
artifacts themselves: a build host PUBLISHES an artifact into the shared
store under its content hash, and every serving host STAGES it by hash —
the fetch re-verifies the full PR-3 validation chain (schema version,
content hash, finite/positive tables) plus that the entry actually IS
the requested hash, so a registry entry can never impersonate another
build.

Entries are directories ``<root>/emulator_artifact/<hash>/`` holding the
standard ``artifact.npz`` + ``manifest.json`` pair (written by
``emulator.artifact.save_artifact``).  Publication is atomic: the pair
is written into a temp directory in the store root and renamed into
place; a loser of a publish race simply discards its temp copy — the
content under a hash is identical by construction.  A corrupt entry is
deleted on fetch (one re-publish, never a poisoned stage).
"""
from __future__ import annotations

import os
import shutil
import sys
import tempfile

from bdlz_tpu.provenance.store import Store

ARTIFACT_KIND = "emulator_artifact"


def publish_artifact(store: Store, artifact) -> str:
    """Publish an :class:`~bdlz_tpu.emulator.artifact.EmulatorArtifact`,
    a seam-split :class:`~bdlz_tpu.emulator.multidomain.MultiDomainArtifact`
    bundle, or an artifact/bundle directory path into ``store``; returns
    the content hash it is addressable by (the COMPOSITE hash for a
    bundle — the whole bundle moves as one unit)."""
    from bdlz_tpu.emulator.artifact import EmulatorArtifact, save_artifact
    from bdlz_tpu.emulator.multidomain import (
        MultiDomainArtifact,
        load_any_artifact,
        save_multidomain_artifact,
    )

    if not isinstance(artifact, (EmulatorArtifact, MultiDomainArtifact)):
        artifact = load_any_artifact(str(artifact))
    content_hash = artifact.content_hash
    dest = os.path.join(store.root, ARTIFACT_KIND, content_hash)
    os.makedirs(os.path.join(store.root, ARTIFACT_KIND), mode=0o700,
                exist_ok=True)
    if os.path.isdir(dest):
        store.stats.hits += 1
        return content_hash  # same hash = same bytes; nothing to do
    tmp = tempfile.mkdtemp(dir=store.root, suffix=".tmp")
    try:
        if isinstance(artifact, MultiDomainArtifact):
            save_multidomain_artifact(tmp, artifact)
        else:
            save_artifact(tmp, artifact)
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            # benign ONLY if a concurrent publisher won the rename
            # (identical content under the same hash); any other rename
            # failure must surface — returning a hash that was never
            # published would strand every later fetch
            if not os.path.isdir(dest):
                raise
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    store.stats.writes += 1
    return content_hash


def reset_fetch_counter(store: Store = None) -> None:
    """Reset the ``registry_fetch`` fault-key counter.

    The counter is scoped PER-STORE (the ``store_read`` pattern —
    :meth:`Store.arm_faults`): every :class:`Store` instance starts at
    zero, so two stores in one process (the multi-tenant plane's
    registry + a test's scratch store) can no longer perturb each
    other's fault keys the way the old process-global counter did.
    With a ``store`` the counter is reset on that instance; without one
    the call is a no-op kept for pre-scoping callers (a fresh store IS
    a fresh counter)."""
    if store is not None:
        store._fetches = 0


def _inject_fetch_fault(fault_plan, key: int, path: str) -> None:
    """Apply an armed ``registry_fetch`` fault to the entry BEFORE the
    load: ``torn`` truncates its payload (the corrupt-entry eviction
    path must detect-and-delete), ``corrupt`` flips one byte (the
    content-hash verification must refuse it).  The damaged file is the
    entry's ``artifact.npz`` when present, its ``manifest.json``
    otherwise (a multi-domain bundle's top level)."""
    for name in ("artifact.npz", "manifest.json"):
        target = os.path.join(path, name)
        if os.path.isfile(target):
            fault_plan.corrupt_file("registry_fetch", key, target)
            fault_plan.corrupt_bytes("registry_fetch", key, target)
            return


def fetch_artifact(store: Store, content_hash: str, fault_plan=None):
    """Load + fully validate the published artifact ``content_hash``
    (kind-dispatched: a single artifact or a multi-domain bundle).

    Raises :class:`~bdlz_tpu.emulator.artifact.EmulatorArtifactError`
    when the entry is absent, fails any load-time validation, or its
    verified hash is not the requested one (an impersonating or
    renamed entry); a corrupt entry is deleted first, so the next
    publish starts clean.  ``fault_plan`` (site ``registry_fetch``,
    keyed by the PER-STORE fetch call counter) exercises exactly
    those refusal paths deterministically — see bdlz_tpu/faults.py."""
    from bdlz_tpu.emulator.artifact import EmulatorArtifactError
    from bdlz_tpu.emulator.multidomain import load_any_artifact

    fetch_key = getattr(store, "_fetches", 0)
    store._fetches = fetch_key + 1
    path = os.path.join(store.root, ARTIFACT_KIND, str(content_hash))
    if fault_plan is not None and os.path.isdir(path):
        _inject_fetch_fault(fault_plan, fetch_key, path)
    if not os.path.isdir(path):
        store.stats.misses += 1
        raise EmulatorArtifactError(
            f"no published emulator artifact {content_hash!r} in store "
            f"{store.root}"
        )
    try:
        artifact = load_any_artifact(path)
    except EmulatorArtifactError:
        print(
            f"[registry] published artifact entry {path} failed validation; "
            "deleting the corrupt entry",
            file=sys.stderr,
        )
        shutil.rmtree(path, ignore_errors=True)
        store.stats.dropped_corrupt += 1
        raise
    if artifact.content_hash != str(content_hash):
        raise EmulatorArtifactError(
            f"registry entry {path} verifies as {artifact.content_hash!r}, "
            f"not the requested {content_hash!r}: refusing the impersonating "
            "entry"
        )
    store.stats.hits += 1
    return artifact


def fetch_artifact_with_retry(
    store: Store, content_hash: str, fault_plan=None, retry=None,
    label: str = "registry_fetch",
):
    """:func:`fetch_artifact` under the shared :class:`RetryPolicy`
    (``utils/retry.py`` — bounded attempts, deterministic backoff,
    injectable sleep).

    The serving tier's registry fetches — the health plane's replica
    re-provision and the multi-tenant plane's cold-artifact admission —
    were single-attempt: one torn read or one lost publish race failed
    the whole re-provision cycle.  A corrupt entry is still deleted on
    the failing attempt (so a retry sees a clean absent entry, never
    the same poisoned bytes), and a publish that lands between attempts
    is admitted — the fetch-vs-publish race resolves to a validated
    artifact or a typed :class:`EmulatorArtifactError`, never a torn
    read.  ``retry=None`` keeps the old single-attempt semantics
    exactly (zero behavior change for callers that do not opt in)."""
    from bdlz_tpu.utils.retry import call_with_retry

    if retry is None:
        return fetch_artifact(store, content_hash, fault_plan=fault_plan)
    from bdlz_tpu.emulator.artifact import EmulatorArtifactError

    return call_with_retry(
        lambda: fetch_artifact(store, content_hash, fault_plan=fault_plan),
        retry,
        label=f"{label}:{content_hash}",
        retryable=(EmulatorArtifactError, OSError),
    )


class ArtifactCache:
    """Local pull-through cache in front of :func:`fetch_artifact`.

    Content addressing makes this trivial: an artifact's hash IS its
    identity, so a locally cached copy can be fully re-validated on
    every hit without talking to the shared store at all.  The cache is
    itself a :class:`Store` (reusing ``publish_artifact`` /
    ``fetch_artifact`` wholesale), so a local hit runs the exact same
    validation chain a registry stage does — a *validated* hit, never a
    trusted one.  A corrupt local entry is evicted loudly on the failing
    hit (the registry's corrupt-entry path: stderr line +
    ``dropped_corrupt``) and re-fetched from the shared store — the
    cache can degrade availability, never poison an answer.

    The serving fabric fronts every cold admission with one of these per
    host: whole-host failover re-admits a dead host's tenants by hash,
    so the second host to serve an artifact pays a local validated load
    instead of a shared-store round trip.  ``counters()`` lands on
    ``ServeStats.extras`` (the opt-in summary extension seam).
    """

    def __init__(self, root: str):
        self.store = Store(root)
        self.hits = 0
        self.misses = 0

    @property
    def evictions(self) -> int:
        """Corrupt local entries evicted (and re-fetched) so far."""
        return self.store.stats.dropped_corrupt

    def fetch(self, store: Store, content_hash: str, fault_plan=None,
              retry=None):
        """Fetch-by-hash through the cache: validated local hit, or
        pull-through from ``store`` (under ``fault_plan``/``retry``
        exactly as :func:`fetch_artifact_with_retry`) + local fill."""
        from bdlz_tpu.emulator.artifact import EmulatorArtifactError

        local = os.path.join(self.store.root, ARTIFACT_KIND,
                             str(content_hash))
        if os.path.isdir(local):
            try:
                artifact = fetch_artifact(self.store, content_hash)
                self.hits += 1
                return artifact
            except EmulatorArtifactError:
                # corrupt (already deleted + counted by fetch_artifact)
                # or impersonating (delete here) — either way the local
                # copy is gone and the shared store is authoritative
                shutil.rmtree(local, ignore_errors=True)
        artifact = fetch_artifact_with_retry(
            store, content_hash, fault_plan=fault_plan, retry=retry,
        )
        publish_artifact(self.store, artifact)
        self.misses += 1
        return artifact

    def counters(self) -> dict:
        """Hit/miss/eviction counters (``ServeStats.extras`` payload)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "corrupt_evictions": self.evictions,
        }


# ---- lease records (the elastic scheduler's claim plane) ----------------
#
# One small JSON record per (job, chunk) under ``lease/`` in the shared
# store.  The *policy* (TTLs, steal-on-expiry, distinct-failure
# quarantine) lives in ``parallel/scheduler.py``; this layer provides
# only the storage primitives, with the one property the policy cannot
# build for itself: an EXCLUSIVE create (``os.link`` of a temp file —
# atomic on POSIX, fails with EEXIST when another worker claimed first).
# Overwrites (heartbeat, steal, complete) go through the store's atomic
# durable JSON write; a lost overwrite race is safe because the commit
# protocol (first ``put_npz`` wins, later commits verify bitwise) — not
# the lease record — is what makes results correct.  A torn/corrupt
# record reads as None (``Store.get_json`` drops it), which the policy
# treats as a free chunk: the worst case is a double-computation the
# commit protocol resolves.

LEASE_KIND = "lease"


def lease_entry_name(job: str, chunk: int) -> str:
    """Store entry name of the lease record for ``(job, chunk)``."""
    return f"{LEASE_KIND}/{job}_{int(chunk):05d}.json"


def read_lease(store: Store, job: str, chunk: int):
    """The lease record dict, or None when absent/torn (torn records are
    evicted by the store and re-claimable — see module comment)."""
    return store.get_json(lease_entry_name(job, chunk))


def write_lease(store: Store, job: str, chunk: int, record) -> str:
    """Atomically overwrite the lease record (heartbeat/steal/complete)."""
    return store.put_json(lease_entry_name(job, chunk), record)


def create_lease(store: Store, job: str, chunk: int, record) -> bool:
    """Atomically create the lease record IFF absent; True when this
    caller won the claim.  mkstemp + ``os.link`` (not ``os.replace``,
    which would silently overwrite a racing winner): the link fails with
    EEXIST when any other worker already holds the name."""
    import json as jsonlib
    import tempfile

    path = store.path_for(lease_entry_name(job, chunk))
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            jsonlib.dump(record, f)
        try:
            os.link(tmp, path)
        except FileExistsError:
            return False
        store.stats.writes += 1
        return True
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
