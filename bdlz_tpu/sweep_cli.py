"""Sweep driver CLI: grid scans from the command line.

The single-point CLI (`bdlz_tpu.cli`) keeps the reference's surface; this
command adds the capability the reference lacks — multi-dimensional
parameter scans on the TPU mesh:

    python -m bdlz_tpu.sweep_cli \\
        --config yields_config_equal_mass.json \\
        --axis "m_chi_GeV=geom:0.1:10:64" --axis "P_chi_to_B=lin:0.01:0.9:16" \\
        --out sweep_out --chunk 8192

Axis syntax: ``name=geom:start:stop:n`` (geomspace), ``lin:start:stop:n``
(linspace), or an explicit comma list ``name=0.1,0.5,1.0``. Outputs land in
``--out`` as chunk .npz files plus a manifest (resumable); a JSON summary
(throughput, failures, best Planck-likelihood point) goes to stdout.
"""
from __future__ import annotations

import argparse
import json
from typing import Dict

import numpy as np  # host-side use only; jitted paths go through the backend.py xp seam (bdlz-lint R1 audit)


def parse_axis(spec: str):
    name, _, rhs = spec.partition("=")
    if not rhs:
        raise ValueError(f"--axis must look like name=geom:a:b:n, got {spec!r}")
    if rhs.startswith(("geom:", "lin:")):
        kind, a, b, n = rhs.split(":")
        a, b, n = float(a), float(b), int(n)
        vals = np.geomspace(a, b, n) if kind == "geom" else np.linspace(a, b, n)
    else:
        vals = np.asarray([float(v) for v in rhs.split(",")])
    return name.strip(), vals


def _run_elastic(args, cfg, static, axes, event_log, interpret):
    """Dispatch one ``--elastic`` role (see parallel/scheduler.py).

    Every role derives the plan from the SAME ``--config``/``--axis``
    flags — nothing spec-level is serialized between processes; the
    store's job record only cross-validates.  Returns the fold-side
    :class:`~bdlz_tpu.parallel.sweep.SweepResult` (local/coordinator),
    or None for the worker role, which prints its own JSON summary."""
    import os
    import sys

    from bdlz_tpu.parallel import (
        WallClock,
        elect_coordinator,
        plan_elastic_sweep,
        run_sweep_elastic,
        run_worker_loop,
    )
    from bdlz_tpu.provenance import resolve_store

    store = resolve_store(args.elastic_store, cfg, label="elastic-cli")
    if store is None:
        raise SystemExit(
            f"--elastic-store {args.elastic_store!r} did not resolve to a "
            "trusted store (check ownership/permissions)"
        )
    worker_id = args.worker_id or f"pid{os.getpid()}"
    common = dict(
        chunk_size=args.chunk, n_y=args.n_y, impl=args.impl,
        interpret=interpret, fuse_exp=args.fuse_exp,
    )
    role = args.elastic
    if role == "auto":
        plan = plan_elastic_sweep(cfg, axes, static, **common)
        won = elect_coordinator(
            store, plan.job, worker_id, ttl_s=args.lease_ttl,
        )
        role = "coordinator" if won else "worker"
        print(f"[elastic] {worker_id}: elected {role}", file=sys.stderr)
    if role == "worker":
        summary = run_worker_loop(
            cfg, axes, static, store=store, worker_id=worker_id,
            lease_ttl_s=args.lease_ttl,
            quarantine_after=args.quarantine_after,
            churn_plan=args.churn_plan, poll_s=args.poll,
            event_log=event_log, **common,
        )
        print(json.dumps({"elastic": "worker", **summary}))
        return None
    # local: deterministic in-process fleet (ManualClock); coordinator:
    # wall clock so lease arithmetic agrees with external workers
    clock = None if role == "local" else WallClock()
    return run_sweep_elastic(
        cfg, axes, static, store=store, n_workers=args.elastic_workers,
        lease_ttl_s=args.lease_ttl, quarantine_after=args.quarantine_after,
        churn_plan=args.churn_plan, clock=clock,
        tick_s=(1.0 if clock is None else args.poll),
        event_log=event_log, **common,
    )


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="bdlz_tpu parameter-sweep driver")
    ap.add_argument("--config", required=True, help="Base yields_config JSON")
    ap.add_argument("--axis", action="append", default=[], required=False,
                    help="Sweep axis, e.g. m_chi_GeV=geom:0.1:10:64 (repeatable)")
    ap.add_argument("--out", default=None, help="Output dir (chunks + manifest; resumable)")
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--n-y", type=int, default=8000, dest="n_y")
    ap.add_argument("--mesh-sp", type=int, default=1,
                    help="Devices reserved for the sp (grid) mesh axis")
    ap.add_argument("--events", default=None,
                    help="Write JSON-lines sweep events to this file")
    ap.add_argument("--profile-dir", default=None,
                    help="Capture a jax.profiler trace per chunk into this dir")
    ap.add_argument("--debug-nans", action="store_true",
                    help="Raise on any NaN produced under jit (sanitizer mode)")
    ap.add_argument("--sanitize", action="store_true",
                    help="Runtime sanitizer: float64 dtype-drift check on "
                         "the sweep outputs at the L4->output boundary. "
                         "Failed points stay in-band NaN by design, so "
                         "finiteness is n_failed's job here; combine with "
                         "--debug-nans to instead abort at the first "
                         "NaN-producing primitive (which includes designed "
                         "failed-point NaNs)")
    ap.add_argument("--impl", default="tabulated",
                    choices=("tabulated", "pallas", "direct", "esdirk",
                             "esdirk_lockstep"),
                    help="Per-point engine: tabulated (XLA fast path), pallas "
                         "(MXU interpolation kernel — fastest on real TPU), "
                         "direct (raw (n_y x n_z) kernel; forced when I_p is swept), "
                         "esdirk (stiff Boltzmann integrator — the lane-repacking "
                         "batch engine; forced when sigma_v, washout, or depletion "
                         "are active), esdirk_lockstep (the legacy single-program "
                         "vmapped stiff loop, kept for A/B)")
    ap.add_argument("--fuse-exp", action="store_true", dest="fuse_exp",
                    help="With --impl pallas: evaluate the merged exponential "
                         "inside the kernel (accurate f32 Cody-Waite exp)")
    ap.add_argument("--quad", default="auto", choices=("auto", "on", "off"),
                    help="y-quadrature on the tabulated engine: auto (default "
                         "— snapped-panel Gauss-Legendre after the "
                         "per-population convergence audit passes, else the "
                         "reference trapezoid, loudly), on (force the panel "
                         "rule, skipping the audit), off (pin the reference "
                         "trapezoid).  Overrides the config's quad_panel_gl "
                         "tri-state; the resolved scheme joins the resume "
                         "manifest hash")
    # shared LZ flag helper (lz/options.py): one home for the
    # --lz-profile/--lz-method/--lz-gamma-phi surface and the
    # scenario-plane flags across the three drivers; this CLI's
    # documented divergence is its "local" default estimator
    from bdlz_tpu.lz.options import (
        SWEEP_METHODS,
        add_bounce_flag,
        add_lz_method_flags,
        add_lz_scenario_flags,
    )

    add_lz_method_flags(
        ap, default="local", choices=SWEEP_METHODS,
        profile_help="Bounce-profile CSV: derive each point's P_chi_to_B "
                     "from its own wall speed through the two-channel LZ "
                     "kernel (v_w scans then exercise the distributed-LZ "
                     "physics end to end)",
        method_help="Per-point LZ estimator with --lz-profile: local "
                    "(analytic composition, spectrally exact — the "
                    "1e-6-contract default), coherent (full transfer "
                    "matrix, carries Stueckelberg oscillations), "
                    "local-momentum (thermal flux-weighted average), "
                    "dephased (density-matrix transport with "
                    "--lz-gamma-phi dephasing)",
    )
    add_lz_scenario_flags(ap)
    add_bounce_flag(ap)
    ap.add_argument("--multihost", action="store_true",
                    help="Initialize jax.distributed from JAX_COORDINATOR_ADDRESS/"
                         "JAX_NUM_PROCESSES/JAX_PROCESS_ID before building the mesh "
                         "(run one identical invocation per host)")
    ap.add_argument("--elastic", default=None,
                    choices=("local", "coordinator", "worker", "auto"),
                    help="Elastic work-stealing mode (parallel/scheduler.py): "
                         "local (in-process fleet, deterministic clock), "
                         "coordinator (drive + fold against external workers, "
                         "wall clock), worker (claim/compute/commit loop only; "
                         "prints a worker summary), auto (lease-elect: first "
                         "process to win the coordinator lease drives, the "
                         "rest work).  Every role re-derives the plan from "
                         "the same --config/--axis flags; drift fails loudly")
    ap.add_argument("--elastic-store", default=None,
                    help="Shared store root for the elastic lease/commit "
                         "plane (required with --elastic)")
    ap.add_argument("--elastic-workers", type=int, default=2,
                    help="In-process fleet size for --elastic local/coordinator")
    ap.add_argument("--worker-id", default=None,
                    help="Stable worker name for --elastic worker/auto "
                         "(default: pid-derived)")
    ap.add_argument("--lease-ttl", type=float, default=60.0,
                    help="Elastic lease TTL in seconds (expired leases are "
                         "stolen/requeued)")
    ap.add_argument("--quarantine-after", type=int, default=3,
                    help="Fleet-quarantine a chunk after it failed on this "
                         "many DISTINCT workers")
    ap.add_argument("--churn-plan", default=None,
                    help="Operational fault plan JSON/path (sites "
                         "worker_crash/lease/store_read) — churn-test "
                         "harness use; never joins result identity")
    ap.add_argument("--poll", type=float, default=1.0,
                    help="Elastic worker/coordinator poll interval (seconds)")
    args = ap.parse_args(argv)
    if args.fuse_exp and args.impl != "pallas":
        ap.error("--fuse-exp requires --impl pallas")
    if args.elastic:
        if not args.elastic_store:
            ap.error("--elastic requires --elastic-store (the shared "
                     "lease/commit plane)")
        if args.multihost:
            ap.error("--elastic and --multihost are mutually exclusive "
                     "(elastic workers are single-process; scale is the fleet)")
        if args.out:
            ap.error("--elastic results are committed to the store; "
                     "--out is the static engine's resume dir")
        if args.profile_dir:
            ap.error("--profile-dir is not supported with --elastic")
        if args.lz_profile:
            ap.error("--lz-profile sweeps are not supported with --elastic "
                     "(profiles are not shipped to workers); drop --elastic")
        if args.bounce:
            ap.error("--bounce sweeps are not supported with --elastic "
                     "(the derived profile is not shipped to workers); "
                     "drop --elastic")
    from bdlz_tpu.lz.options import bounce_flag_error, lz_flags_error

    _gerr = bounce_flag_error(args) or lz_flags_error(
        args, default_method="local"
    )
    if _gerr:
        ap.error(_gerr)
    if args.lz_mode in ("chain", "thermal") and not (
        args.lz_profile or args.bounce
    ):
        ap.error(f"--lz-mode {args.lz_mode} derives P per point from a "
                 "bounce profile; pass --lz-profile or --bounce")

    if args.multihost:
        from bdlz_tpu.parallel import init_multihost

        init_multihost()
    else:
        # A dead accelerator relay would hang the first backend touch
        # forever; probe and pin CPU instead (never in multihost runs,
        # where the distributed runtime owns platform selection).
        from bdlz_tpu.utils.platform import ensure_live_backend

        ensure_live_backend("sweep")

    import jax

    from bdlz_tpu.backend import ensure_x64

    ensure_x64()
    if args.sanitize:
        from bdlz_tpu import sanitize

        # no jax_debug_nans arm here: the sweep engine reports failed
        # points as in-band NaN by design, and debug-nans would abort on
        # the first one — that stricter mode stays opt-in (--debug-nans)
        sanitize.enable(jax_nans=False)
    if args.debug_nans:
        from bdlz_tpu.utils.profiling import enable_nan_debugging

        enable_nan_debugging(True)

    from bdlz_tpu.config import load_config, static_choices_from_config, validate
    from bdlz_tpu.constants import PLANCK_DM_OVER_B
    from bdlz_tpu.parallel import make_mesh, run_sweep

    # the sweep engine always executes on the JAX path — strict validation
    cfg = validate(load_config(args.config), backend="tpu")
    # explicit scenario flags override the config's lz_* keys (the --quad
    # pattern); the RESOLVED mode flows through StaticChoices into the
    # engine dispatch and every identity (docs/scenarios.md)
    from bdlz_tpu.lz.options import apply_scenario_flags

    cfg = apply_scenario_flags(cfg, args)
    if cfg.lz_mode != "two_channel":
        if not (args.lz_profile or args.bounce):
            raise SystemExit(
                f"lz_mode={cfg.lz_mode!r} derives P per point from a bounce "
                "profile; pass --lz-profile or --bounce"
            )
        # a config-driven scenario mode forbids the two-channel estimator
        # knobs it would silently ignore (the flag-driven case is caught
        # by lz_flags_error above)
        if args.lz_method != "local" or args.lz_gamma_phi:
            raise SystemExit(
                f"--lz-method/--lz-gamma-phi have no effect with "
                f"lz_mode={cfg.lz_mode!r} (the scenario owns the kernel)"
            )
    axes: Dict[str, np.ndarray] = dict(parse_axis(s) for s in args.axis)
    if not axes:
        raise SystemExit("at least one --axis is required")

    if args.elastic:
        mesh = None  # elastic workers are single-process; scale is the fleet
    else:
        n_dev = len(jax.devices())
        sp = max(1, args.mesh_sp)
        if n_dev % sp:
            raise SystemExit(
                f"--mesh-sp {sp} does not divide device count {n_dev}"
            )
        mesh = make_mesh(shape=(n_dev // sp, sp))

    event_log = None
    if args.events:
        from bdlz_tpu.utils.logging import EventLog

        event_log = EventLog(path=args.events)

    static = static_choices_from_config(cfg)
    if args.quad != "auto":
        static = static._replace(quad_panel_gl=args.quad == "on")

    interpret = args.impl == "pallas" and jax.devices()[0].platform == "cpu"
    if args.elastic:
        res = _run_elastic(args, cfg, static, axes, event_log, interpret)
        if res is None:
            return  # worker role: its summary is already printed
    else:
        res = run_sweep(
            cfg, axes, static,
            mesh=mesh, chunk_size=args.chunk, n_y=args.n_y, out_dir=args.out,
            event_log=event_log, trace_dir=args.profile_dir,
            impl=args.impl, interpret=interpret, fuse_exp=args.fuse_exp,
            lz_profile=args.lz_profile, lz_method=args.lz_method,
            lz_gamma_phi=args.lz_gamma_phi, bounce=args.bounce,
        )

    if args.sanitize:
        from bdlz_tpu import sanitize

        # L4 -> output boundary: dtype drift is a hard error; failed
        # points are reported as in-band NaN by design, so finiteness is
        # res.n_failed's job, not the sanitizer's
        sanitize.check_tree(
            "L4:solver -> output (sweep)", res.outputs, allow_nan=True
        )

    ratios = res.outputs["DM_over_B"]
    finite = np.isfinite(ratios)
    if finite.any():
        best = int(np.argmin(np.abs(np.where(finite, ratios, np.inf) - PLANCK_DM_OVER_B)))
        # recover the best point's axis values from its flat index (C-order grid)
        shape = tuple(len(v) for v in axes.values())
        best_idx = np.unravel_index(best, shape)
        closest = {
            "index": best,
            "DM_over_B": float(ratios[best]),
            "target": PLANCK_DM_OVER_B,
            "params": {
                name: float(vals[i]) for (name, vals), i in zip(axes.items(), best_idx)
            },
        }
    else:
        closest = None  # every point failed; keep the summary strict JSON
    print(json.dumps({
        # omit-at-default, like the identity rule: two-channel summaries
        # stay byte-identical to pre-scenario output
        **({"lz_mode": cfg.lz_mode} if cfg.lz_mode != "two_channel" else {}),
        **({"elastic": args.elastic} if args.elastic else {}),
        "n_points": res.n_points,
        "n_failed": res.n_failed,
        "n_quarantined": res.n_quarantined,
        "n_retries": res.n_retries,
        "seconds": round(res.seconds, 3),
        "points_per_sec": round(res.points_per_sec, 1),
        "resumed_chunks": res.resumed_chunks,
        "quad_impl": res.quad_impl,
        "n_quad_nodes": res.n_quad_nodes,
        "out_dir": res.out_dir,
        "closest_to_planck": closest,
    }))


if __name__ == "__main__":
    main()
