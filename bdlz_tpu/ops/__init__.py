"""Custom ops: the tabulated KJMA kernel and (future) pallas kernels."""
from bdlz_tpu.ops.kjma_table import KJMATable, eval_f_table, make_f_table

__all__ = ["KJMATable", "make_f_table", "eval_f_table"]
