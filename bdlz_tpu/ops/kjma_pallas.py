"""Pallas TPU kernel for the tabulated-KJMA quadrature hot loop.

Why this kernel exists: the sweep engine's fast path is, per y-node, a
4-tap cubic interpolation into a 16384-entry F(y) table
(:mod:`bdlz_tpu.ops.kjma_table`).  Expressed as `values[idx]` that is an
XLA gather, and measured on a v5e chip the gather alone is ~90% of the
whole pipeline's runtime (XLA TPU lowers small-table gathers to a slow
serial form; measurements in `docs/perf_notes.md`).  TPUs have no
hardware gather, but they have a 128x128 systolic array — so this kernel
reformulates the lookup as dense MXU work:

* the table is laid out as a transposed (4*128, 128) matrix of four
  flat-shifted copies, ``T4[k*128 + c, m] = F[m*128 + c + k - 1]`` — the
  shifts bake the cubic stencil's row-crossing into the layout;
* nodes are streamed in (ncol, 128) tiles: 128 consecutive nodes run
  along the *lane* axis of each sublane row (Mosaic's block tiling wants
  lane-dim blocks of exactly 128, sublane blocks of 8);
* per column, the table *row* per node is selected by a one-hot
  ``(512,128) @ (128,128)`` matmul against the transposed table (exact
  in f32 — each output is a copy of one table entry, no summation error;
  the table is BUILT transposed so the in-kernel contraction is the
  canonical (1,0) form), and the *column* taps by a one-hot sublane mask
  + reduction (again exact; plain VPU ops, no dynamic indexing for
  Mosaic to trip on);
* the Pallas grid is 2-D ``(P, ncol/COL_BLOCK)`` — the batch axis times
  column *blocks* of COL_BLOCK sublane rows (default 8, tunable via
  BDLZ_PALLAS_COL_BLOCK at import), so the kernel jaxpr is
  O(1) in n_y.  (A first version statically unrolled a Python loop over
  all ~n_y/128 columns; the jaxpr grew linearly and blew Mosaic's
  recursive lowering with a RecursionError at n_y=8000 — the grid is
  the fix.)
* the cubic Lagrange combine and the multiply by the precomputed
  integrand prefactor happen in-register; by default (``reduce=True``)
  each grid step Kahan-accumulates its (COL_BLOCK, 128) tile into VMEM
  scratch and only compensated (P, COL_BLOCK, 128) sum+compensation
  pairs leave the kernel — n_y/2048 times less HBM writeback than
  streaming the integrand back (4x at the production n_y=8000), and the
  per-point emulated-f64 reduction outside the kernel shrinks from n_y
  to 1024 elements.  ``reduce=False`` streams the full integrand (kept
  for A/B timing).

Everything precision-critical (y-node generation, table index/fraction,
the exp arguments, thermodynamic prefactors) is computed OUTSIDE the
kernel in f64 by XLA — Mosaic has no f64 — and enters as three f32/i32
streams, so the kernel's only error terms are the f32 rounding of the
prefactor and the interpolation arithmetic (~1e-7 relative, tested).
The final trapezoid accumulation is done outside in f64.

Scalar semantics match the reference quadrature
(`first_principles_yields.py:231-267`): y-support clips, e^y clamp at
+-50, the hard A/V=0 cut above y=+50, Gaussian window, and the analytic
|dT/dy| Jacobian — identical to :mod:`bdlz_tpu.solvers.quadrature`, which
remains the bit-parity reference path.
"""
from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np  # host prep + trace-static np.int32 pinning (bdlz-lint R1 audit; see inline suppressions)

from bdlz_tpu.config import PointParams
from bdlz_tpu.constants import PI
from bdlz_tpu.ops.kjma_table import KJMATable, Y_CLAMP
from bdlz_tpu.physics.thermo import relativistic_density_coeff
from bdlz_tpu.solvers.quadrature import quadrature_bounds

Array = Any

f32 = jnp.float32
f64 = jnp.float64
i32 = jnp.int32

#: Table geometry: N entries as (ROWS x LANES), four stencil-shifted copies.
ROWS = 128
LANES = 128

#: Lane columns (of 128 nodes each) handled per Pallas grid step.  Small
#: static unroll: big enough to amortize per-step overhead, small enough
#: that the kernel jaxpr stays tiny (the grid, not the unroll, walks n_y).
#: Tunable at import via BDLZ_PALLAS_COL_BLOCK (multiples of 8 — the f32
#: sublane tile — so block shapes stay Mosaic-aligned): the hardware
#: shootout sweeps it per-subprocess to find the grid-overhead sweet
#: spot; a non-default value joins the sweep resume identity
#: (`parallel/sweep.py`).
COL_BLOCK_DEFAULT = 8
COL_BLOCK = int(
    os.environ.get("BDLZ_PALLAS_COL_BLOCK", str(COL_BLOCK_DEFAULT))
)
if COL_BLOCK < 8 or COL_BLOCK % 8:
    raise ValueError(
        f"BDLZ_PALLAS_COL_BLOCK must be a positive multiple of 8 (the f32 "
        f"sublane tile), got {COL_BLOCK}"
    )


def pallas_evidence_row() -> dict:
    """Evidence-row fragment self-describing the kernel tuning knobs.

    Each knob (COL_BLOCK, the bf16x3 table split) is labeled whenever
    its env var was explicitly set — even to the default, so the
    collector sweeps' default legs stay distinguishable from unlabeled
    rows — or its value differs from the default.  Callers splice it
    only on pallas-path rows.
    """
    row = {}
    if "BDLZ_PALLAS_COL_BLOCK" in os.environ or COL_BLOCK != COL_BLOCK_DEFAULT:
        row["pallas_col_block"] = COL_BLOCK
    if "BDLZ_PALLAS_TABLE_SPLIT3" in os.environ:
        row["pallas_table_split3"] = TABLE_SPLIT3
    return row


#: Default for the in-kernel Kahan reduction.  The sweep resume identity
#: references THIS constant (`parallel/sweep.py`), so flipping it — e.g.
#: reverting to the streaming kernel after a hardware regression —
#: invalidates pallas sweep directories instead of silently splicing
#: chunks from two summation algorithms.
REDUCE_DEFAULT = True


#: Rows of the stencil-shifted table layout (4 cubic taps × 128 lanes).
STENCIL_ROWS = 4 * LANES

#: Effective value of the bf16x3 masked-split table layout knob (see
#: `build_shifted_table`); import-time like COL_BLOCK so the hardware
#: shootout can A/B it per-subprocess (BDLZ_PALLAS_TABLE_SPLIT3=1).
#: Strict "0"/"1" parsing: a typo'd value must fail fast, not silently
#: bench the f32 layout as a duplicate of the baseline.
_TABLE_SPLIT3_RAW = os.environ.get("BDLZ_PALLAS_TABLE_SPLIT3", "0")
if _TABLE_SPLIT3_RAW not in ("0", "1"):
    raise ValueError(
        f"BDLZ_PALLAS_TABLE_SPLIT3 must be '0' or '1', "
        f"got {_TABLE_SPLIT3_RAW!r}"
    )
TABLE_SPLIT3 = _TABLE_SPLIT3_RAW == "1"


def _split3_masked(t4: np.ndarray) -> np.ndarray:
    """(3·512, 128) bf16-exact mantissa-masked split of an f32 table.

    Each f32 value's 24-bit mantissa is cut into three 8-bit pieces by
    TRUNCATING bitmasks (top 16 bits of the f32 pattern are exactly a
    bf16 value; the residual subtraction is exact in f32), so
    ``x == p0 + p1 + p2`` bit-exactly for every value whose third piece
    stays in bf16's subnormal range (exponent ≥ −133 + 16) — all normal
    table entries.  The ~30 f32-subnormal entries of a production F
    table (the F → 0 underflow tail near y = +50) reconstruct to within
    2⁻¹³³ absolute — ~1e-34 relative on Y_B, far inside the 1e-6
    contract.  Unlike a naive 2-piece ROUNDED bf16 split (~1e-5 rel
    err), this is the exact form of the one-hot contraction at 3 bf16
    MXU passes instead of fp32's ~6.
    """
    x = t4.astype(np.float32).copy()
    pieces = []
    for _ in range(3):
        hi = (x.view(np.uint32) & np.uint32(0xFFFF0000)).view(np.float32)
        pieces.append(hi)
        x = x - hi  # exact: hi is x truncated, same binade
    return np.concatenate(pieces, axis=0)


def build_shifted_table(
    table: KJMATable, split3: "bool | None" = None
) -> jax.Array:
    """Stencil-shifted TRANSPOSED layout of an F table for the kernel.

    ``T4[k*128 + c, m] = F[clip(m*128 + c + k - 1, 0, N-1)]`` for the four
    cubic taps k = 0..3 (offsets -1..+2 around the base index).  Built
    once per sweep on the host, already transposed so the in-kernel
    row-select is the canonical (1,0)-contraction matmul; the edge clips
    are unreachable in use because the base index is clipped to [1, N-3]
    (matching `eval_f_table`).

    ``split3`` (default: the BDLZ_PALLAS_TABLE_SPLIT3 env knob,
    ``TABLE_SPLIT3``) selects
    the (3·512, 128) bf16 mantissa-masked layout instead of the
    (512, 128) f32 one — the kernel dispatches on the table's static
    shape, so both layouts run through the same entry points
    (`_split3_masked` documents the exactness argument).
    """
    flat = np.asarray(table.values, dtype=np.float64)
    n = flat.shape[0]
    if n % LANES != 0:
        raise ValueError(f"table size {n} must be a multiple of {LANES}")
    rows = n // LANES
    if rows > ROWS:
        raise ValueError(f"table rows {rows} exceed one-hot width {ROWS}")
    cols = []
    for k in range(4):
        idx = np.clip(np.arange(n) + k - 1, 0, n - 1)
        block = flat[idx].reshape(rows, LANES)
        if rows < ROWS:  # pad to the fixed one-hot width
            block = np.pad(block, ((0, ROWS - rows), (0, 0)))
        cols.append(block)
    t4 = np.concatenate(cols, axis=1).T.astype(np.float32)
    if split3 is None:
        split3 = TABLE_SPLIT3
    if split3:
        return jnp.asarray(_split3_masked(t4), dtype=jnp.bfloat16)
    return jnp.asarray(t4, dtype=f32)


#: Cody–Waite constants for the in-kernel f32 exp: ln2 split so n*LN2_HI is
#: exact for |n| < 2^12, plus log2(e).
_LOG2E = 1.4426950408889634
_LN2_HI = 0.693359375
_LN2_LO = -2.1219444005469057e-4


def exp_neg_f32(a_hi, a_lo):
    """Accurate f32 e^(a_hi + a_lo) (rel err ~2e-7, flush below -87).

    Designed for the normalized exponents of the fused kernel (a <= 0
    after peak subtraction) but correct over the whole f32-representable
    domain up to a ~ +87 (the 2^n scale construction clamps n to the
    normal-exponent range) — tested on [-87, +40].

    The TPU VPU's native f32 exp is only ~7e-6 accurate (measured on v5e) —
    an order of magnitude outside the 1e-6 parity contract — so the kernel
    uses Cody–Waite range reduction (n = round(a*log2e); r = a - n*ln2 via
    the hi/lo split so the reduction is exact) and a degree-7 Taylor
    polynomial on r in [-0.35, 0.35] (truncation ~1e-9), scaled by 2^n
    built from exponent bits.  The argument arrives as an exact two-piece
    f64 split (|a_lo| <= ulp(a_hi)) so large-magnitude arguments lose
    nothing to the f32 cast.  Pure jnp ops: works identically inside a
    Pallas kernel and in plain XLA (where the tests pin it against f64).
    """
    n = jnp.round(a_hi * f32(_LOG2E))
    r = (a_hi - n * f32(_LN2_HI)) - n * f32(_LN2_LO)
    r = r + a_lo
    # e^r via Horner, degree 7 (truncation ~1e-9 on |r| <= 0.35)
    p = f32(1.0 / 5040.0)
    p = p * r + f32(1.0 / 720.0)
    p = p * r + f32(1.0 / 120.0)
    p = p * r + f32(1.0 / 24.0)
    p = p * r + f32(1.0 / 6.0)
    p = p * r + f32(0.5)
    p = p * r + f32(1.0)
    p = p * r + f32(1.0)
    # 32-bit-pinned constants: weak 64-bit scalars break Mosaic lowering
    # under x64 (see `_interp_column`).
    ni = jnp.clip(n.astype(i32), np.int32(-126), np.int32(127))
    scale = jax.lax.bitcast_convert_type((ni + np.int32(127)) << np.int32(23), f32)
    out = p * scale
    return jnp.where(a_hi < f32(-87.0), f32(0.0), out)


def split_f64(x):
    """Exact two-piece f32 split of an f64 array: x == hi + lo + O(1e-14)."""
    hi = x.astype(f32)
    lo = (x - hi.astype(f64)).astype(f32)
    return hi, lo


def _interp_column(t4t, subl, i1t, st, j):
    """Cubic F-interpolation for column j of a (COL_BLOCK, 128) node tile.

    Nodes live along the LANE axis (Mosaic requires lane-dim blocks of
    128, so the column axis sits on sublanes).  The table *row* per node
    is selected by a one-hot contraction on the MXU — exact in f32: each
    output is a copy of one table entry, no summation error — and the
    *column* taps by a one-hot sublane mask + sublane reduction (also
    exact; plain VPU ops, no dynamic indexing for Mosaic to trip on),
    then the Lagrange cubic combine.  Shared by both kernel variants.

    Every scalar constant is pinned to a strong 32-bit dtype: under
    jax_enable_x64 a bare Python int/float stages as a weak 64-bit
    constant, and Mosaic's 64->32 convert lowering recurses infinitely
    (`_convert_helper` re-emits the convert it is lowering) — the
    RecursionError that killed this kernel on hardware in r2/r3.
    """
    lanes = np.int32(LANES)
    idx = i1t[j:j + 1, :]                       # (1, 128) node base indices
    r = idx // lanes
    c = idx - r * lanes
    rsel = (subl == r).astype(f32)              # (128, 128): [m, n] = m == r[n]
    # picked[k*128+cc, n] = t4t[k*128+cc, r[n]]: the table arrives
    # transposed, so this is the canonical (1,0)-contraction matmul —
    # the best-trodden Mosaic lowering path.  The design's exactness
    # rests on each output being a bit-exact COPY of one f32 table
    # entry, so the contraction must not round the table operand:
    #
    # * f32 layout (512, 128): precision pinned to HIGHEST
    #   (#tpu.contract_precision<fp32>) — Mosaic's default, like
    #   XLA-TPU's for f32 dots, may demote operands to one bf16 MXU
    #   pass (~4e-3 rel err; the preflight would catch it only by
    #   degrading the whole engine to tabulated).
    # * bf16x3 layout (3·512, 128): three mantissa-masked bf16 pieces
    #   summing bit-exactly to the f32 values (`_split3_masked`), each
    #   contracted against the bf16-exact one-hot in a single DEFAULT
    #   pass — 3 MXU passes instead of fp32's ~6; picked for A/B via
    #   BDLZ_PALLAS_TABLE_SPLIT3, dispatched on the static table shape.
    if t4t.shape[0] == 3 * STENCIL_ROWS:
        r16 = rsel.astype(jnp.bfloat16)  # 0/1: exact in bf16
        picked = jnp.zeros((STENCIL_ROWS, LANES), f32)
        for p in range(3):
            picked = picked + jnp.dot(
                t4t[p * STENCIL_ROWS:(p + 1) * STENCIL_ROWS, :], r16,
                preferred_element_type=f32,
            )
    else:
        picked = jnp.dot(
            t4t, rsel, preferred_element_type=f32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (512, 128)
    csel = (subl == c).astype(f32)              # (128, 128): [cc, n] = cc == c[n]
    s = st[j:j + 1, :]
    sm1, s0, s1_, s2 = s + f32(1.0), s, s - f32(1.0), s - f32(2.0)
    w = (
        -(s0 * s1_ * s2) * f32(1.0 / 6.0),
        (sm1 * s1_ * s2) * f32(0.5),
        -(sm1 * s0 * s2) * f32(0.5),
        (sm1 * s0 * s1_) * f32(1.0 / 6.0),
    )
    acc = jnp.zeros((1, LANES), f32)
    for k in range(4):
        fk = jnp.sum(
            picked[k * LANES:(k + 1) * LANES, :] * csel, axis=0, keepdims=True
        )
        acc = acc + w[k] * fk
    return acc


def _build_tile(ghat_ref, i1_ref, s_ref, t4_ref):
    """Integrand tile of one (point, column-block) grid step:
    (COL_BLOCK, 128) nodes -> ``ghat * cubic_interp(F)``.  The batch axis
    and the column axis both live in the Pallas grid, so this body (and
    its jaxpr) is O(1) in n_y.  Shared by the streaming and reducing
    kernels — one copy of the interpolation math per variant."""
    t4t = t4_ref[:]         # (512, 128) f32 (transposed table), in VMEM
    ghat = ghat_ref[0]      # (COL_BLOCK, 128) f32
    i1t = i1_ref[0]         # (COL_BLOCK, 128) i32
    st = s_ref[0]           # (COL_BLOCK, 128) f32
    subl = jax.lax.broadcasted_iota(i32, (ROWS, LANES), 0)

    rows = [
        ghat[j:j + 1, :] * _interp_column(t4t, subl, i1t, st, j)
        for j in range(COL_BLOCK)
    ]
    return jnp.concatenate(rows, axis=0)


def _build_tile_fused(g2_ref, ahi_ref, alo_ref, i1_ref, s_ref, t4_ref):
    """Fused-exponent integrand tile: ``g2 * exp_neg_f32(a_hi + a_lo) * F``
    — the prep then does no per-node transcendental at all (the f64 exp
    was its largest remaining cost under TPU f64 emulation)."""
    t4t = t4_ref[:]
    g2 = g2_ref[0]
    i1t = i1_ref[0]
    st = s_ref[0]
    subl = jax.lax.broadcasted_iota(i32, (ROWS, LANES), 0)

    e = exp_neg_f32(ahi_ref[0], alo_ref[0])  # whole tile at once

    rows = [
        g2[j:j + 1, :] * e[j:j + 1, :] * _interp_column(t4t, subl, i1t, st, j)
        for j in range(COL_BLOCK)
    ]
    return jnp.concatenate(rows, axis=0)


def _kernel(ghat_ref, i1_ref, s_ref, t4_ref, out_ref):
    out_ref[0] = _build_tile(ghat_ref, i1_ref, s_ref, t4_ref)


def _kernel_fused(g2_ref, ahi_ref, alo_ref, i1_ref, s_ref, t4_ref, out_ref):
    out_ref[0] = _build_tile_fused(g2_ref, ahi_ref, alo_ref, i1_ref, s_ref, t4_ref)


def _kahan_accumulate(tile, acc_ref, comp_ref, sum_ref, cmp_ref, jb, njb):
    """Kahan-add one (COL_BLOCK, 128) integrand tile into VMEM scratch.

    The column-block axis of the grid revisits the same point, so the
    scratch accumulators (initialized at jb == 0) carry the partial sums
    across grid steps; the final step writes both the compensated sum and
    the running compensation to the outputs, letting the host reconstruct
    the column sums to ~f64 quality from two f32 streams (the trapezoid
    weights are pre-folded into the tile, so the host-side work left is a
    1024-element f64 dot per point instead of n_y)."""
    from jax.experimental import pallas as pl

    @pl.when(jb == np.int32(0))
    def _init():
        acc_ref[...] = jnp.zeros((COL_BLOCK, LANES), f32)
        comp_ref[...] = jnp.zeros((COL_BLOCK, LANES), f32)

    acc = acc_ref[...]
    comp = comp_ref[...]
    y = tile - comp
    t = acc + y
    comp_ref[...] = (t - acc) - y
    acc_ref[...] = t

    @pl.when(jb == np.int32(njb - 1))
    def _finish():
        sum_ref[0] = acc_ref[...]
        cmp_ref[0] = comp_ref[...]


def _kernel_reduce(ghat_ref, i1_ref, s_ref, t4_ref, sum_ref, cmp_ref,
                   acc_ref, comp_ref):
    """`_kernel` with the trapezoid accumulation fused into the kernel.

    Instead of writing the full (P, n_y) integrand back to HBM (and
    summing it in emulated f64 on the host side of the pallas_call), each
    grid step Kahan-accumulates its tile in VMEM and only (P, COL_BLOCK,
    128) sum+compensation pairs leave the kernel: n_y/2048 times less HBM
    writeback (4x at the production n_y=8000) and the per-point
    emulated-f64 reduction outside shrinks from n_y to 1024 elements."""
    from jax.experimental import pallas as pl

    _kahan_accumulate(
        _build_tile(ghat_ref, i1_ref, s_ref, t4_ref),
        acc_ref, comp_ref, sum_ref, cmp_ref,
        pl.program_id(1), pl.num_programs(1),
    )


def _kernel_fused_reduce(g2_ref, ahi_ref, alo_ref, i1_ref, s_ref, t4_ref,
                         sum_ref, cmp_ref, acc_ref, comp_ref):
    """`_kernel_fused` with the in-kernel Kahan accumulation."""
    from jax.experimental import pallas as pl

    _kahan_accumulate(
        _build_tile_fused(g2_ref, ahi_ref, alo_ref, i1_ref, s_ref, t4_ref),
        acc_ref, comp_ref, sum_ref, cmp_ref,
        pl.program_id(1), pl.num_programs(1),
    )


def _tile_specs(n_streams: int, table_rows: int = STENCIL_ROWS):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Index-map constants are np.int32-pinned: under x64 a bare `0`
    # stages as i64 and Mosaic fails to legalize the index function's
    # `func.return` (i64 operand).
    zero = np.int32(0)  # bdlz-lint: disable=R1 — trace-time static scalar, pinned on purpose
    stream = pl.BlockSpec(
        (1, COL_BLOCK, ROWS), lambda p, jb: (p, jb, zero), memory_space=pltpu.VMEM
    )
    table = pl.BlockSpec(
        (table_rows, ROWS), lambda p, jb: (zero, zero), memory_space=pltpu.VMEM
    )
    return [stream] * n_streams + [table], pl.BlockSpec(
        (1, COL_BLOCK, ROWS), lambda p, jb: (p, jb, zero), memory_space=pltpu.VMEM
    )


def _reduced_call(
    kernel, n_streams: int, P: int, ncol: int, interpret: bool,
    table_rows: int = STENCIL_ROWS,
):
    """pallas_call wrapper for the in-kernel-reduction variants."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    in_specs, _ = _tile_specs(n_streams, table_rows)
    zero = np.int32(0)  # bdlz-lint: disable=R1 — trace-time static scalar, pinned on purpose
    partial_spec = pl.BlockSpec(
        (1, COL_BLOCK, ROWS), lambda p, jb: (p, zero, zero),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kernel,
        grid=(P, ncol // COL_BLOCK),
        in_specs=in_specs,
        out_specs=[partial_spec, partial_spec],
        out_shape=[
            jax.ShapeDtypeStruct((P, COL_BLOCK, ROWS), f32),
            jax.ShapeDtypeStruct((P, COL_BLOCK, ROWS), f32),
        ],
        scratch_shapes=[
            pltpu.VMEM((COL_BLOCK, ROWS), f32),
            pltpu.VMEM((COL_BLOCK, ROWS), f32),
        ],
        interpret=interpret,
    )


def interp_multiply(
    ghat: jax.Array,
    i1: jax.Array,
    sfrac: jax.Array,
    t4: jax.Array,
    *,
    interpret: bool = False,
    reduce: bool = False,
) -> "jax.Array | list[jax.Array]":
    """``ghat * cubic_interp(F, i1 + sfrac)`` for (P, ncol, 128) tiles.

    With ``reduce=True`` the trapezoid accumulation happens in-kernel and
    the return is a pair of (P, COL_BLOCK, 128) compensated partial sums
    (Kahan sum + compensation) instead of the full integrand."""
    from jax.experimental import pallas as pl

    P, ncol, rows = ghat.shape
    assert rows == ROWS and ncol % COL_BLOCK == 0
    if reduce:
        return _reduced_call(
            _kernel_reduce, 3, P, ncol, interpret, t4.shape[0]
        )(ghat, i1, sfrac, t4)
    in_specs, out_spec = _tile_specs(3, t4.shape[0])
    return pl.pallas_call(
        _kernel,
        grid=(P, ncol // COL_BLOCK),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((P, ncol, ROWS), f32),
        interpret=interpret,
    )(ghat, i1, sfrac, t4)


def interp_multiply_fused(
    g2: jax.Array,
    a_hi: jax.Array,
    a_lo: jax.Array,
    i1: jax.Array,
    sfrac: jax.Array,
    t4: jax.Array,
    *,
    interpret: bool = False,
    reduce: bool = False,
) -> "jax.Array | list[jax.Array]":
    """``g2 * e^(a_hi+a_lo) * cubic_interp(F, i1 + sfrac)`` on tiles.

    With ``reduce=True`` the return is the [sum, compensation] pair of
    (P, COL_BLOCK, 128) partials (see `interp_multiply`)."""
    from jax.experimental import pallas as pl

    P, ncol, rows = g2.shape
    assert rows == ROWS and ncol % COL_BLOCK == 0
    if reduce:
        return _reduced_call(
            _kernel_fused_reduce, 5, P, ncol, interpret, t4.shape[0]
        )(g2, a_hi, a_lo, i1, sfrac, t4)
    in_specs, out_spec = _tile_specs(5, t4.shape[0])
    return pl.pallas_call(
        _kernel_fused,
        grid=(P, ncol // COL_BLOCK),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((P, ncol, ROWS), f32),
        interpret=interpret,
    )(g2, a_hi, a_lo, i1, sfrac, t4)


def _to_tiles(a: jax.Array, n_y: int, ncol: int, fill) -> jax.Array:
    """(P, n_y) node-major -> (P, ncol, 128) tiles, padded.

    Node n = col*128 + lane: 128 consecutive nodes run along the lane
    axis of each column row — a plain reshape, no transpose."""
    P = a.shape[0]
    pad = ROWS * ncol - n_y
    if pad:
        a = jnp.concatenate([a, jnp.full((P, pad), fill, a.dtype)], axis=1)
    return a.reshape(P, ncol, ROWS)


def integrate_YB_pallas(
    pp: PointParams,
    chi_stats: str,
    table: KJMATable,
    t4: jax.Array,
    n_y: int = 8000,
    *,
    interpret: bool = False,
    fuse_exp: bool = False,
    reduce: bool = REDUCE_DEFAULT,
) -> jax.Array:
    """Batched fast-path Y_B with the Pallas interpolation kernel.

    ``pp`` is a PointParams *of arrays* (shape (P,) per leaf) — unlike the
    per-point `integrate_YB_quadrature_tabulated` this handles the batch
    itself (the kernel grid IS the batch axis), so callers pass the whole
    chunk rather than vmapping.  Semantics per point are identical to the
    tabulated path; deviation is ~1e-7 relative (f32 streams), validated
    against it in tests and by the bench accuracy gate.

    The stream prep exploits the closed-form map T = T_p·d^{-1/2} with
    d = 1 + 2y/(β/H) to fold every power law into per-point scalars
    (emulated f64 on TPU makes per-node transcendentals the cost center):

    * relativistic branch: n_eq·v̄·|dT/dy|/(s·H·T) collapses to a constant
      — even the Hubble factors cancel against the β in the A/V prefactor,
      leaving 45/(2π²·g*s)·(I_p/2)/v_w;
    * Maxwell–Boltzmann branch: the same collapse leaves a single √d and
      the Boltzmann exponent −m/T = −(m/T_p)√d;
    * the A/V e^{clamp(y)}, the Gaussian window, and the MB exponent merge
      into ONE f64 exp per node, normalized by its analytic per-point
      maximum (the window–growth product peaks at y* = min(σ², clamp)) so
      the f32 stream cannot under/overflow.
    """
    xp = jnp
    n_y = max(int(n_y), 2000)
    # Columns of 128 nodes, rounded up to whole COL_BLOCK grid steps; the
    # pad nodes carry zero integrand weight (fill values below).
    ncol = -(-n_y // (ROWS * COL_BLOCK)) * COL_BLOCK

    y_lo, y_hi = quadrature_bounds(pp, xp)
    ys = xp.linspace(y_lo, y_hi, n_y, axis=-1)          # (P, n_y) f64

    B_safe = xp.maximum(pp.beta_over_H, 1e-30)
    d = xp.maximum(1.0 + 2.0 * ys / B_safe[:, None], 1e-12)
    sqrt_d = xp.sqrt(d)

    # --- per-point scalars (f64) ---
    g_chi = pp.g_chi
    c_n = relativistic_density_coeff(1.0, chi_stats) * g_chi
    m_eff = xp.maximum(pp.m_chi_GeV, 1e-20)  # mean-speed mass floor (ref :117)
    c_m = g_chi * (pp.m_chi_GeV / (2.0 * PI)) ** 1.5 * xp.sqrt(8.0 / (PI * m_eff))
    # All Hubble/entropy powers cancel analytically (see docstring):
    KK = (
        pp.P
        * pp.flux_scale
        * 0.25
        * c_n
        * (table.I_p / 2.0)
        * (45.0 / (2.0 * PI**2 * pp.g_star_s))
        / xp.maximum(pp.v_w, 1e-12)
    )
    bf_ratio = c_m / (c_n * pp.T_p_GeV)

    # --- merged exponent (ONE f64 exp per node) ---
    sig = xp.maximum(pp.sigma_y, 1e-6)[:, None]
    yc = xp.clip(ys, -Y_CLAMP, Y_CLAMP)
    aw = yc - (ys * ys) / (2.0 * sig * sig)
    # branch predicate T > m/3 via the computed sqrt_d (3 T_p > m √d)
    rel = 3.0 * pp.T_p_GeV[:, None] > pp.m_chi_GeV[:, None] * sqrt_d
    # -m/T = -(m/T_p)√d exactly; the reference's max(T, 1e-30) exponent
    # floor (:105) only differs for T < 1e-30, where both forms underflow
    # exp() to zero for any m > 0.
    mb_arg = (pp.m_chi_GeV / pp.T_p_GeV)[:, None] * sqrt_d
    A = aw - xp.where(rel, 0.0, mb_arg)
    # analytic maximum of aw over the interval (MB term only lowers A):
    # aw is increasing up to min(σ², +clamp) and decreasing after, so the
    # interval argmax is that point clipped into [y_lo, y_hi]; the VALUE
    # must apply the same e^y clamp as aw itself (windows entirely below
    # -Y_CLAMP otherwise understate the max and feed the kernel exp
    # positive arguments).
    y_star = xp.clip(xp.minimum(sig[:, 0] ** 2, Y_CLAMP), y_lo, y_hi)
    A_max = xp.clip(y_star, -Y_CLAMP, Y_CLAMP) - (y_star * y_star) / (
        2.0 * sig[:, 0] ** 2
    )

    bf = xp.where(rel, 1.0, bf_ratio[:, None] * sqrt_d)

    # Trapezoid weights on the uniform y grid, folded into the stream so
    # the final accumulation is a plain f64 sum.
    dy = (y_hi - y_lo) / (n_y - 1)
    wtrap = xp.ones((n_y,), f64).at[0].set(0.5).at[-1].set(0.5) * dy[:, None]

    t = (yc - table.y0) * table.inv_dy
    n = table.values.shape[0]
    i1 = xp.clip(xp.floor(t).astype(i32), 1, n - 3)
    sfrac = (t - i1).astype(f32)
    i1_t = _to_tiles(i1, n_y, ncol, 1)
    s_t = _to_tiles(sfrac, n_y, ncol, 0.0)

    if fuse_exp:
        # The exponential moves into the kernel (exp_neg_f32 on an exact
        # two-piece argument); prep ships only bf·wtrap and the split args.
        g2 = bf * wtrap
        g2 = xp.where(ys > Y_CLAMP, 0.0, g2)  # hard A/V = 0 cut (ref :159)
        gscale = xp.max(xp.abs(g2), axis=-1, keepdims=True)
        g2 = g2 / xp.maximum(gscale, 1e-300)
        a_hi, a_lo = split_f64(A - A_max[:, None])
        out = interp_multiply_fused(
            _to_tiles(g2.astype(f32), n_y, ncol, 0.0),
            _to_tiles(a_hi, n_y, ncol, 0.0),
            _to_tiles(a_lo, n_y, ncol, 0.0),
            i1_t,
            s_t,
            t4,
            interpret=interpret,
            reduce=reduce,
        )
    else:
        g = xp.exp(A - A_max[:, None]) * bf * wtrap
        g = xp.where(ys > Y_CLAMP, 0.0, g)  # hard A/V = 0 cut (reference :159)
        # Normalize per point before the f32 cast: with the exponent already
        # peak-normalized the stream is O(dy), but the per-point max keeps the
        # f32 cast safe for every parameter corner (the scale re-enters in f64).
        gscale = xp.max(xp.abs(g), axis=-1, keepdims=True)
        g = g / xp.maximum(gscale, 1e-300)
        out = interp_multiply(
            _to_tiles(g.astype(f32), n_y, ncol, 0.0), i1_t, s_t, t4,
            interpret=interpret,
            reduce=reduce,
        )
    if reduce:
        # Kahan reconstruction: the true sum of each lane column is
        # acc - comp to O(eps^2), so only (COL_BLOCK x 128) partials per
        # point cross into emulated f64 instead of the n_y-node integrand.
        ssum, scomp = out
        total = xp.sum(ssum.astype(f64) - scomp.astype(f64), axis=(1, 2))
    else:
        total = xp.sum(out.astype(f64), axis=(1, 2))
    YB = KK * xp.exp(A_max) * gscale[:, 0] * total
    return xp.where(y_hi > y_lo, YB, 0.0)


def pallas_preflight(
    chi_stats: str = "fermion",
    n_points: int = 128,
    n_y: int = 2000,
    fuse_exp: bool = False,
    tol: float = 1e-6,
    table_n: int = 16384,
    reduce: bool = REDUCE_DEFAULT,
):
    """Compile-and-compare the kernel on a tiny chunk, on THIS platform.

    Mosaic lowering failures are platform-specific: the interpret-mode
    tests pass on CPU while the real TPU compile can still die (the r2
    kernel's RecursionError did exactly that, silently downgrading the
    round's benchmark to the fallback engine).  This preflight runs the
    real ``pallas_call`` on a 128-point chunk and compares against the
    pure-XLA tabulated path, so lowering regressions fail loudly and
    cheaply before a long sweep.  Returns ``(ok, max_rel_err, detail)``
    and never raises: a compile/runtime error comes back as
    ``(False, inf, message)``.

    Callers MUST pass the shapes they are about to run (``n_y``,
    ``table_n``, ``chi_stats``, ``fuse_exp``): lowering failures are
    shape-dependent — the r2 RecursionError fired at n_y = 8000 but not
    at small column counts — so a preflight at a different shape proves
    nothing about the sweep it gates.
    """
    import numpy as _np

    try:
        from bdlz_tpu.config import config_from_dict, static_choices_from_config
        from bdlz_tpu.models.yields_pipeline import point_yields_fast
        from bdlz_tpu.ops.kjma_table import make_f_table
        from bdlz_tpu.parallel.sweep import build_grid

        base = config_from_dict(
            {
                "regime": "nonthermal",
                "P_chi_to_B": 0.14925839040304145,
                "source_shape_sigma_y": 9.0,
                "incident_flux_scale": 1.07e-9,
                "Y_chi_init": 4.90e-10,
            }
        )
        static = static_choices_from_config(base)._replace(chi_stats=chi_stats)
        table = make_f_table(base.I_p, jnp, n=table_n)
        t4 = build_shifted_table(table)
        rng = _np.random.default_rng(0)
        # span both n_eq branches (heavy-mass points push T_p below m/3)
        grid = build_grid(
            base,
            {
                "m_chi_GeV": _np.concatenate(
                    [rng.uniform(0.1, 5.0, n_points - 2), [300.0, 900.0]]
                ),
                "T_p_GeV": rng.uniform(30.0, 300.0, n_points),
                "v_w": rng.uniform(0.05, 0.95, n_points),
            },
            product=False,
        )
        grid = jax.tree.map(jnp.asarray, grid)
        got = _np.asarray(
            integrate_YB_pallas(
                grid, chi_stats, table, t4, n_y=n_y, fuse_exp=fuse_exp,
                reduce=reduce,
            )
        )
        ref = _np.asarray(
            jax.vmap(lambda p: point_yields_fast(p, static, table, jnp, n_y=n_y).Y_B)(
                grid
            )
        )
        rel = float(_np.max(_np.abs(got - ref) / _np.abs(ref)))
        ok = bool(_np.all(_np.isfinite(got)) and rel <= tol)
        return ok, rel, f"rel_err={rel:.3e} on {n_points} pts (tol {tol:g})"
    except Exception as exc:  # noqa: BLE001 — preflight must report, not raise
        return False, float("inf"), f"{type(exc).__name__}: {exc}"


def point_yields_pallas(
    pp: PointParams,
    static,
    table: KJMATable,
    t4: jax.Array,
    n_y: int = 8000,
    *,
    interpret: bool = False,
    fuse_exp: bool = False,
    reduce: bool = REDUCE_DEFAULT,
):
    """Batched flagship pipeline on the Pallas hot path.

    Drop-in batched analog of ``jax.vmap(point_yields_fast)`` — same
    YieldsResult fields, same regime semantics (reference :376-384,
    :413-417) — with the KJMA interpolation running on the MXU.
    """
    from bdlz_tpu.models.yields_pipeline import final_Y_chi_quadrature, present_day

    Y_B = integrate_YB_pallas(
        pp, static.chi_stats, table, t4, n_y, interpret=interpret,
        fuse_exp=fuse_exp, reduce=reduce,
    )
    Y_chi = jax.vmap(lambda p: final_Y_chi_quadrature(p, static, jnp))(pp)
    return present_day(Y_B, Y_chi, pp.m_chi_GeV, pp.m_B_kg, jnp)
