"""Tabulated KJMA shape function — the sweep engine's fast path.

The KJMA area-to-volume kernel factorises as

    [A/V](y) = (I_p/2)·(β/v_w)·e^y · F(y; I_p),
    F(y; I_p) = ∫ z² e^{−z} exp(−(I_p/6) e^{clamp(y)} γ₄(z)) dz,

where the z-integral is, by the reference's contract, the trapezoid on the
*fixed* grid linspace(0, 30, 1200) (`first_principles_yields.py:154-164`).
Measured fact (see tests): the archived golden outputs are tied to that
exact discretisation — the z-integral is *not* converged in nz (doubling nz
moves Y_B by ~26%), so any "better" z-quadrature would break the ≤1e-6
contract against the SciPy reference. The scheme is the spec.

That makes F a 1-D function of y alone for fixed I_p (all other sweep
parameters — T_p, β/H, v_w, g* — enter only the prefactor). A parameter
sweep with fixed I_p therefore needs the expensive (n_y × n_z) tensor
*once*, to build a dense table of F over the clamped domain y ∈ [−50, 50],
after which every (point, y) evaluation is a 4-point Lagrange interpolation
— ~2.4e6 transcendentals per point collapse to ~2e3 fused multiply-adds.
This is the designed hot path for the TPU sweep engine (vmap over points,
batch axis sharded over the mesh); the direct tensor path remains as the
bit-parity reference.

Accuracy: F is smooth in y (log-curvature set by γ₄ moments); on the
default 16384-node table the cubic interpolation error is ≤1e-9 relative
(validated in tests against the direct kernel), far inside the 1e-6
contract.
"""
from __future__ import annotations

from typing import Any, NamedTuple

from bdlz_tpu.physics.percolation import KJMAGrid, make_kjma_grid

Array = Any

Y_CLAMP = 50.0  # e^y clamp of the reference kernel (:161)


class KJMATable(NamedTuple):
    """Dense F(y) table for one I_p (all arrays backend-native)."""

    y0: Any        # first node (= −Y_CLAMP)
    inv_dy: Any    # 1 / node spacing
    values: Array  # F at the nodes, shape (n,)
    I_p: Any       # the I_p this table was built for


def make_f_table(
    I_p,
    xp,
    n: int = 16384,
    grid: KJMAGrid | None = None,
) -> KJMATable:
    """Build the F(y) table with the exact reference z-trapezoid.

    Cost: one (n × 1200) tensor — paid once per sweep, not per point.

    The table VALUES are always computed with host NumPy when possible
    (concrete ``I_p``/``grid``) and only then shipped to the requested
    namespace: the accuracy audit attributes the dominant platform drift
    of the tabulated fast path to this build step (f64 ``exp`` differs
    between NumPy, XLA-CPU, and TPU-emulated f64 — stage table in
    ``scripts/accuracy_audit.py`` artifacts), and a once-per-sweep host
    build is free.  A traced ``I_p`` (e.g. inside jit) falls back to the
    in-namespace build.
    """
    import numpy as _np

    if xp is not _np:
        try:
            host = make_f_table(
                float(I_p), _np, n=n,
                grid=None if grid is None
                else KJMAGrid(*(_np.asarray(a) for a in grid)),  # bdlz-lint: disable=R3 — deliberate host build (accuracy-audit drift attribution)
            )
            return KJMATable(
                y0=host.y0, inv_dy=host.inv_dy,
                values=xp.asarray(host.values), I_p=I_p,
            )
        except _tracer_errors():
            pass  # traced inputs: build in-namespace below
    if grid is None:
        grid = make_kjma_grid(xp)
    ys = xp.linspace(-Y_CLAMP, Y_CLAMP, n)
    expy = xp.exp(ys)
    integrand = grid.weight * xp.exp(-(I_p / 6.0) * expy[:, None] * grid.gamma4)
    F = xp.trapezoid(integrand, grid.z, axis=-1)
    dy = (2.0 * Y_CLAMP) / (n - 1)
    return KJMATable(y0=-Y_CLAMP, inv_dy=1.0 / dy, values=F, I_p=I_p)


def table_to_namespace(table: KJMATable, xp) -> KJMATable:
    """Ship a (host-built) table's VALUES into another array namespace.

    The one sanctioned way to reuse a host-NumPy table on a device
    backend (the sweep engine and the bench both audit on the host table
    and run on its device copy): only the dense value array converts —
    the scalar metadata stays host-side — so the device table is the
    SAME table, bit-for-bit, not a near-copy from a second build.
    """
    return KJMATable(
        y0=table.y0, inv_dy=table.inv_dy,
        values=xp.asarray(table.values), I_p=table.I_p,
    )


def _tracer_errors():
    """ONLY the tracer-concretization error types: a genuine failure in
    the host build (bad grid payload, None I_p) must propagate, not
    silently fall back to the drift-prone in-namespace build."""
    from jax.errors import ConcretizationTypeError, TracerArrayConversionError

    return (ConcretizationTypeError, TracerArrayConversionError)


def cubic_lagrange_uniform(t: Array, values: Array, xp) -> Array:
    """4-point Lagrange interpolation of uniform-grid ``values`` at
    fractional index ``t``, batched and trace-safe (pure gathers + FMAs).

    The shared stencil core of every dense lookup table in the package
    (the KJMA F(y) table here, the P(v_w) table in ``lz.sweep_bridge``):
    base index clipped to [1, n-3] so the (−1, 0, 1, 2) offsets stay in
    bounds — queries at the domain edges evaluate exactly to the boundary
    nodes when ``t`` itself is clipped by the caller.
    """
    n = values.shape[0]
    i1 = xp.clip(xp.floor(t).astype("int32"), 1, n - 3)
    s = t - i1  # in [−?, 2]; nodes at offsets (−1, 0, 1, 2) around i1

    f_m1 = values[i1 - 1]
    f_0 = values[i1]
    f_1 = values[i1 + 1]
    f_2 = values[i1 + 2]

    # Lagrange basis on equispaced offsets −1, 0, 1, 2.
    sm1 = s + 1.0
    s0 = s
    s1 = s - 1.0
    s2 = s - 2.0
    w_m1 = -(s0 * s1 * s2) / 6.0
    w_0 = (sm1 * s1 * s2) / 2.0
    w_1 = -(sm1 * s0 * s2) / 2.0
    w_2 = (sm1 * s0 * s1) / 6.0
    return w_m1 * f_m1 + w_0 * f_0 + w_1 * f_1 + w_2 * f_2


def eval_f_table(y: Array, table: KJMATable, xp) -> Array:
    """F(clamp(y)) by 4-point (cubic) Lagrange interpolation, batched.

    Trace-safe: pure gathers + FMAs, vmap/jit/shard-friendly. Queries are
    clamped to the table domain, matching the kernel's e^y clamp — above
    +50 the *caller* applies the hard A/V = 0 cut, as in the direct path.
    """
    t = (xp.clip(y, -Y_CLAMP, Y_CLAMP) - table.y0) * table.inv_dy
    return cubic_lagrange_uniform(t, table.values, xp)


def area_over_volume_tabulated(
    y: Array,
    beta_over_H,
    T_p,
    v_w,
    g_star,
    table: KJMATable,
    xp,
) -> Array:
    """[A/V](y) via the F-table — semantics of the direct kernel
    (`percolation.area_over_volume`) with F interpolated instead of
    integrated."""
    from bdlz_tpu.physics.thermo import hubble_rate

    beta = beta_over_H * hubble_rate(T_p, g_star, xp)
    expy = xp.exp(xp.clip(y, -Y_CLAMP, Y_CLAMP))
    pref = (table.I_p / 2.0) * (beta / xp.maximum(v_w, 1e-12)) * expy
    F = eval_f_table(y, table, xp)
    return xp.where(y > Y_CLAMP, 0.0, pref * F)
