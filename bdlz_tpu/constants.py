"""Physical constants and unit conversions (framework layer L0).

Numerical values match the reference pipeline exactly
(/root/reference/first_principles_yields.py:33-39) so that the NumPy
execution path reproduces the archived golden outputs bit-for-bit.
"""
from __future__ import annotations

import math

#: Riemann zeta(3), used in relativistic equilibrium number densities.
ZETA3: float = 1.202056903159594

PI: float = math.pi

#: Planck mass in GeV entering H = 1.66 sqrt(g*) T^2 / M_Pl.
MPL_GEV: float = 1.220890e19

#: Radiation-domination Hubble prefactor: H = HUBBLE_COEFF sqrt(g*) T^2 / M_Pl
#: (the sqrt(8 pi^3/90) ~ 1.66 convention of the reference, :84).
HUBBLE_COEFF: float = 1.66

#: Present-day entropy density, cm^-3 and m^-3.
S0_CM3: float = 2891.0
S0_M3: float = S0_CM3 * 1e6

#: GeV -> kg mass conversion.
GEV_TO_KG: float = 1.78266192e-27

#: Proton mass in kg (CODATA).
M_PROTON_KG: float = 1.67262192369e-27

#: Planck 2018 target for Omega_DM / Omega_b (reference PDF section 7, Eq. 22).
PLANCK_DM_OVER_B: float = 5.357

#: Critical density / h^2, kg m^-3 (Planck-normalisation for Omega h^2).
RHO_CRIT_OVER_H2_KG_M3: float = 1.87834e-26

#: Planck 2018 baryon / cold-DM density measurements (TT,TE,EE+lowE+lensing).
PLANCK_OMEGA_B_H2: float = 0.02237
PLANCK_OMEGA_B_H2_SIGMA: float = 0.00015
PLANCK_OMEGA_DM_H2: float = 0.1200
PLANCK_OMEGA_DM_H2_SIGMA: float = 0.0012
