"""Runtime sanitizer layer (the ``--sanitize`` flag on the CLIs).

The static pass (:mod:`bdlz_tpu.lint`) catches structural regressions;
this module catches the *numerical* ones at run time:

* ``jax_debug_nans`` on the JAX path (any NaN produced under jit raises
  with a traceback), enabled through the backend.py config seam;
* finiteness assertions at the layer boundaries of the yields pipeline —
  L1 thermo → L2 percolation → L3 source → L4 solver → output — so a NaN
  names the layer that produced it instead of surfacing as a NaN in
  ``yields_out.json`` three layers later;
* a dtype-drift check asserting the float64 contract end-to-end on both
  backends (a stray float32 literal silently erodes the 1e-6 accuracy
  contract long before it becomes visibly wrong).

Disabled (the default), every hook is a dict-lookup no-op, so the
bit-reproducible NumPy path and the jitted TPU path are byte-for-byte
unchanged — ``tests/test_sanitize.py`` pins that. Enabled, concrete
(host-visible) values are checked; traced values are skipped (they have
no data yet), which is why the single-point CLI evaluates the pipeline
eagerly under ``--sanitize``: every boundary then sees concrete arrays,
and ``jax_debug_nans`` still covers the primitive level.
"""
from __future__ import annotations

from typing import Any, Iterable, Tuple

import numpy as np  # the sanitizer IS the host boundary (bdlz-lint R1 audit)

#: The canonical layer-boundary names (ARCHITECTURE.md layer map).
BOUNDARY_THERMO = "L1:thermo -> L2:percolation"
BOUNDARY_PERCOLATION = "L2:percolation -> L3:source"
BOUNDARY_SOURCE = "L3:source -> L4:solver"
BOUNDARY_SOLVER = "L4:solver -> output"

_STATE = {"enabled": False}


class SanitizerError(RuntimeError):
    """A finiteness or dtype violation, tagged with its layer boundary."""

    def __init__(self, boundary: str, name: str, detail: str) -> None:
        self.boundary = boundary
        self.name = name
        super().__init__(
            f"sanitizer tripped at layer boundary [{boundary}]: "
            f"quantity {name!r} {detail}"
        )


def enable(jax_nans: bool = True) -> None:
    """Arm the sanitizer; optionally also arm ``jax_debug_nans``.

    ``jax_nans=False`` keeps pure-NumPy runs from paying JAX start-up.
    """
    _STATE["enabled"] = True
    if jax_nans:
        from bdlz_tpu.backend import set_debug_nans

        set_debug_nans(True)


def disable() -> None:
    """Disarm every check (does not touch ``jax_debug_nans``)."""
    _STATE["enabled"] = False


def is_enabled() -> bool:
    return _STATE["enabled"]


def _host_view(value: Any):
    """A host ndarray view of ``value``, or None for traced/abstract values."""
    try:
        return np.asarray(value)  # bdlz-lint: disable=R1 — the sanitizer's job is this host sync
    except Exception:
        return None  # tracers carry no data; jax_debug_nans covers them


def _check_leaf(boundary: str, name: str, value: Any, allow_nan: bool) -> None:
    """The one home of the dtype + finiteness contract for one quantity."""
    arr = _host_view(value)
    if arr is None:
        return
    if arr.dtype.kind == "f" and arr.dtype != np.float64:
        raise SanitizerError(
            boundary,
            name,
            f"drifted to dtype {arr.dtype} (float64 contract)",
        )
    # concrete host arrays only (the tracer guard above): the sanitizer's
    # host-side finiteness scan is its whole purpose
    if (  # bdlz-lint: disable=R2 — concrete host array, not a tracer
        not allow_nan
        and arr.dtype.kind in "fc"
        and not np.all(np.isfinite(arr))  # bdlz-lint: disable=R1
    ):
        n_bad = int(np.size(arr) - np.count_nonzero(np.isfinite(arr)))  # bdlz-lint: disable=R1
        raise SanitizerError(
            boundary,
            name,
            f"contains {n_bad} non-finite element(s) "
            f"(shape {arr.shape}, dtype {arr.dtype})",
        )


def checkpoint(boundary: str, **named: Any) -> None:
    """Assert every named quantity is finite f64 at a layer boundary.

    No-op unless :func:`enable` ran. Called between the pipeline layers
    (see :mod:`bdlz_tpu.solvers.quadrature`) and at the CLI output
    boundary; under tracing it degrades to a no-op per value.
    """
    if not _STATE["enabled"]:
        return
    for name, value in named.items():
        _check_leaf(boundary, name, value, allow_nan=False)


def check_tree(boundary: str, tree: Any, allow_nan: bool = False) -> None:
    """Checkpoint every leaf of a NamedTuple/dict/sequence of arrays.

    ``allow_nan=True`` keeps the dtype-drift check but skips finiteness —
    the sweep engine reports failed points as in-band NaN by design.
    """
    if not _STATE["enabled"]:
        return
    for name, leaf in _named_leaves(tree):
        _check_leaf(boundary, name, leaf, allow_nan)


def _named_leaves(tree: Any) -> Iterable[Tuple[str, Any]]:
    if hasattr(tree, "_asdict"):
        yield from tree._asdict().items()
    elif isinstance(tree, dict):
        yield from tree.items()
    elif isinstance(tree, (list, tuple)):
        for i, leaf in enumerate(tree):
            yield f"[{i}]", leaf
    else:
        yield "value", tree
