"""Baryon source-term model (framework layer L3).

S_B(T) = P_{χ→B} · N_flux · J_χ(T) · [A/V](y(T)) · W(y), paper Eqs. 13-15;
reference `first_principles_yields.py:225-228`.

Only the Gaussian window lives here as a named function. The S_B *product*
is deliberately assembled inline at each consumer (quadrature integrand,
Boltzmann RHS, diagnostics table) rather than through a shared helper: the
reference inlines it at each site with *different* floating-point
association orders (:260-264 vs :277 vs :437), and the NumPy backend's
bit-reproducibility contract requires matching each site's order exactly.
"""
from __future__ import annotations

from typing import Any

Array = Any


def source_window(y: Array, sigma_y: Array, xp) -> Array:
    """Gaussian envelope W(y) = exp(−y²/2σ_y²) with σ_y floored at 1e-6.

    Reference `first_principles_yields.py:227` / :262.
    """
    return xp.exp(-0.5 * (y / xp.maximum(sigma_y, 1e-6)) ** 2)
