"""Percolation-time maps and the KJMA area-to-volume kernel (layer L2).

The KJMA kernel is *the* hot spot of the reference pipeline: there it is a
scalar-in/scalar-out method called 8000 times per parameter point through a
Python list comprehension (`first_principles_yields.py:158-165` and :261,
measured 21.7 µs/call ≈ 75% of a point's runtime). Here it is a pure,
batched function: all y-values at once against a fixed z-grid, one
(n_y × n_z) elementwise tensor and one trapezoid reduction — XLA fuses the
whole thing into a single pass suitable for the TPU VPU, and `vmap` extends
it across parameter sweeps with no Python in the loop.

Scalar semantics (floors, clamps, cut-offs) match the reference exactly:

* ``y_of_T`` floors T at 1e-30 (reference :128);
* ``T_of_y`` returns T_p·1e6 when the inverse-map denominator ≤ 1e-12
  (reference :133-134);
* A/V is hard-zeroed for y > 50, e^y is clamped to y ∈ [−50, 50], and the
  wall velocity is floored at 1e-12 (reference :146, :159-161).
"""
from __future__ import annotations

from typing import Any, NamedTuple

from bdlz_tpu.physics.thermo import hubble_rate

Array = Any

#: Default z-grid extent and resolution (reference `AoverVKernel.__init__`,
#: `first_principles_yields.py:142`).
Z_MAX_DEFAULT: float = 30.0
NZ_DEFAULT: int = 1200


def y_of_T(T: Array, T_p: Array, beta_over_H: Array, xp) -> Array:
    """Percolation time variable y(T) = ½ (β/H)_p [(T_p/T)² − 1].

    Closed form for radiation domination with constant g* (paper Eq. 10);
    reference `first_principles_yields.py:126-128`.
    """
    return 0.5 * beta_over_H * ((T_p / xp.maximum(T, 1e-30)) ** 2 - 1.0)


def T_of_y(y: Array, T_p: Array, beta_over_H: Array, xp) -> Array:
    """Inverse map T(y) = T_p / √(1 + 2y/B); T_p·1e6 outside the sensible range.

    Reference `first_principles_yields.py:130-135` (dead code there). The
    quadrature solver inlines its own copy of this map because it needs the
    reference's *other* guard variant (floor the denominator at 1e-12,
    :252-254) for bit parity; this function keeps the documented
    out-of-range → T_p·1e6 contract for library users.
    """
    denom = 1.0 + 2.0 * y / xp.maximum(beta_over_H, 1e-30)
    safe = xp.maximum(denom, 1e-12)
    return xp.where(denom <= 1e-12, T_p * 1e6, T_p / xp.sqrt(safe))


class KJMAGrid(NamedTuple):
    """Precomputed z-quadrature data for the KJMA integral.

    ``z``       — the quadrature nodes, linspace(0, z_max, nz);
    ``weight``  — z² e^{−z}, the y-independent part of the integrand;
    ``gamma4``  — γ₄(z) = 6 − e^{−z}(z³ + 3z² + 6z + 6), the incomplete-Γ
                  factor of the KJMA extended-volume integral (paper Eq. 12).
    """

    z: Array
    weight: Array
    gamma4: Array


def make_kjma_grid(xp, z_max: float = Z_MAX_DEFAULT, nz: int = NZ_DEFAULT) -> KJMAGrid:
    """Build the fixed z-grid (reference `first_principles_yields.py:154-156`)."""
    z = xp.linspace(0.0, z_max, nz)
    ez = xp.exp(-z)
    gamma4 = 6.0 - ez * (z**3 + 3.0 * z**2 + 6.0 * z + 6.0)
    return KJMAGrid(z=z, weight=z**2 * ez, gamma4=gamma4)


def area_over_volume(
    y: Array,
    I_p: Array,
    beta_over_H: Array,
    T_p: Array,
    v_w: Array,
    g_star: Array,
    grid: KJMAGrid,
    xp,
) -> Array:
    """KJMA bubble-wall area per unit volume [A/V](y)  [GeV], batched over y.

    [A/V](y) = (I_p/2)(β/v_w) e^y ∫₀^∞ dz z² e^{−z} exp(−(I_p/6) e^y γ₄(z)),
    paper Eqs. 11-12; scalar semantics of reference
    `first_principles_yields.py:158-165`. ``y`` may have any shape; the
    z-axis is appended for the reduction and contracted by the trapezoid.
    """
    H_p = hubble_rate(T_p, g_star, xp)
    beta = beta_over_H * H_p
    v_w_safe = xp.maximum(v_w, 1e-12)

    y_arr = xp.asarray(y)
    expy = xp.exp(xp.clip(y_arr, -50.0, 50.0))
    prefactor = (I_p / 2.0) * (beta / v_w_safe) * expy

    # (..., n_z) tensor: broadcast e^y against the fixed z-grid. This is the
    # batched replacement for the reference's per-scalar 1200-point loop.
    exponent = -(I_p / 6.0) * expy[..., None] * grid.gamma4
    integrand = grid.weight * xp.exp(exponent)
    F = xp.trapezoid(integrand, grid.z, axis=-1)

    return xp.where(y_arr > 50.0, 0.0, prefactor * F)
