"""Backend-neutral physics kernels (layers L1-L3 of the framework).

Every function is pure, vectorized, and written against an array namespace
``xp`` (``numpy`` or ``jax.numpy``) so the identical formula serves both the
bit-reproducible CPU reference path and the jitted TPU path.
"""
from bdlz_tpu.physics.thermo import (
    hubble_rate,
    entropy_density,
    n_chi_equilibrium,
    mean_speed_chi,
    wall_flux,
)
from bdlz_tpu.physics.percolation import (
    y_of_T,
    T_of_y,
    KJMAGrid,
    make_kjma_grid,
    area_over_volume,
)
from bdlz_tpu.physics.source import source_window

__all__ = [
    "hubble_rate",
    "entropy_density",
    "n_chi_equilibrium",
    "mean_speed_chi",
    "wall_flux",
    "y_of_T",
    "T_of_y",
    "KJMAGrid",
    "make_kjma_grid",
    "area_over_volume",
    "source_window",
]
