"""Thermodynamics / cosmology library (framework layer L1).

Pure, branchless, broadcastable functions over an array namespace ``xp``.
Scalar semantics reproduce the reference pipeline exactly
(`first_principles_yields.py:84-123`), including its numerical guard rails:

* the hard relativistic/non-relativistic branch at ``T > m/3`` in both the
  equilibrium density and the mean speed (reference :95 and :113 — the
  discontinuity is part of the archived numbers, so the predicate must be
  identical on every backend);
* the ``max(T, 1e-30)`` floor inside the Boltzmann exponent (reference :105);
* the ``max(m, 1e-20)`` floor in the mean speed (reference :117).

Statistics strings follow the reference convention: anything starting with
"ferm" (case-insensitive) is a fermion; everything else is a boson
(reference :96).
"""
from __future__ import annotations

from typing import Any

from bdlz_tpu.constants import HUBBLE_COEFF, MPL_GEV, PI, ZETA3

Array = Any


def is_fermion(stats: str) -> bool:
    """Reference statistics-string convention (`first_principles_yields.py:96`)."""
    return str(stats).lower().startswith("ferm")


def relativistic_density_coeff(g: float, stats: str) -> float:
    """Coefficient c in n_rel = c * T^3 (fermion: 3ζ3/4π² per dof; boson: ζ3/π²)."""
    if is_fermion(stats):
        return g * (3.0 * ZETA3 / (4.0 * PI**2))
    return g * (ZETA3 / (PI**2))


def hubble_rate(T: Array, g_star: Array, xp) -> Array:
    """Radiation-domination Hubble rate H = 1.66 √g* T²/M_Pl  [GeV].

    Paper Eq. 2; reference `first_principles_yields.py:84-85`.
    """
    return HUBBLE_COEFF * xp.sqrt(g_star) * T * T / MPL_GEV


def entropy_density(T: Array, g_star_s: Array, xp) -> Array:
    """Entropy density s = (2π²/45) g*_s T³  [GeV³].

    Paper Eq. 3; reference `first_principles_yields.py:87-88`.
    """
    return (2.0 * PI**2 / 45.0) * g_star_s * T**3


def n_chi_equilibrium(T: Array, m: Array, g: float, stats: str, xp) -> Array:
    """Equilibrium χ number density n_eq(T) [GeV³], piecewise at T = m/3.

    Relativistic branch (T > m/3): c_rel · T³ with the spin-statistics
    coefficient; Maxwell–Boltzmann branch otherwise:
    g (m/2π)^{3/2} T^{3/2} e^{−m/T}, with the exponent argument floored at
    T ≥ 1e-30. Reference `first_principles_yields.py:90-107`.
    """
    T = xp.asarray(T)  # scalar inputs go through array ops, like the reference
    c_rel = relativistic_density_coeff(g, stats)
    relativistic = c_rel * T**3
    mb_coeff = g * (m / (2.0 * PI)) ** 1.5
    boltzmann = mb_coeff * T**1.5 * xp.exp(-m / xp.maximum(T, 1e-30))
    return xp.where(T > m / 3.0, relativistic, boltzmann)


def mean_speed_chi(T: Array, m: Array, xp) -> Array:
    """Mean χ speed: 1 when relativistic (T > m/3), else √(8T/(π m)).

    The mass is floored at 1e-20 and the sqrt argument clipped at 0,
    matching reference `first_principles_yields.py:109-120`.
    """
    T = xp.asarray(T)  # scalar inputs go through array ops, like the reference
    thermal_sq = 8.0 * T / (PI * xp.maximum(m, 1e-20))
    thermal = xp.sqrt(xp.maximum(thermal_sq, 0.0))
    return xp.where(T > m / 3.0, 1.0, thermal)


def wall_flux(T: Array, m: Array, g: float, stats: str, xp) -> Array:
    """Kinetic-theory flux onto the wall J_χ = ¼ n_eq v̄  [GeV³].

    Paper Eq. 13; reference `first_principles_yields.py:122-123`.
    """
    return 0.25 * n_chi_equilibrium(T, m, g, stats, xp) * mean_speed_chi(T, m, xp)
