"""Output layer: the `yields_out.json` artifact.

Schema is the reference contract (`first_principles_yields.py:423-427`):
``{"inputs": {<20 reference keys in declaration order>, "P_used": P},
"final": {Y_B, Y_chi, rho_B_kg_m3, rho_DM_kg_m3, DM_over_B}}``. Framework
extension keys are appended to "inputs" only when they differ from their
defaults, so a pure reference run produces a byte-identical file.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict

from bdlz_tpu.config import REFERENCE_KEYS, Config, default_config
from bdlz_tpu.models.yields_pipeline import YieldsResult


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename into it survives host crash.

    ``os.replace`` makes a write atomic against concurrent readers, but
    the rename itself lives in the directory's metadata — until that is
    flushed, a power loss can roll the entry back to the old (or no)
    file even though the caller was told the commit happened.  Best
    effort: platforms/filesystems that refuse ``open(O_RDONLY)`` on a
    directory keep the old (atomic-but-not-durable) behavior.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_json(
    path: str, payload: Any, durable: bool = False, **dump_kwargs: Any
) -> None:
    """Write ``payload`` as JSON to ``path`` atomically (mkstemp + replace).

    THE manifest-write primitive for every resumable artifact in the repo
    (sweep chunk manifests, MCMC checkpoint manifests, emulator
    artifacts): a direct ``json.dump`` into the final path can be torn by
    a crash mid-write, and a torn manifest corrupts resume state — the
    exact failure the manifests exist to survive.  The temp file lives in
    the destination directory so ``os.replace`` is a same-filesystem
    atomic rename (the pattern proven in ``validation.py``'s reference
    cache); concurrent readers see either the old complete file or the
    new complete file, never half a write.

    ``durable`` additionally fsyncs the temp file before the rename and
    the parent directory after it, so the committed entry survives host
    crash/power loss — the provenance store passes it because the
    elastic lease protocol treats a committed chunk as *done forever*
    (a commit that evaporates would strand the sweep's merge).  Default
    off: manifest/chunk-file writers re-validate on resume, so they pay
    only atomicity.
    """
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f, **dump_kwargs)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(d)
    except BaseException:
        # never leave the temp file behind on a failed dump/rename
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, durable: bool = False) -> None:
    """Write ``text`` to ``path`` atomically (mkstemp + replace).

    The plain-text sibling of :func:`atomic_write_json` — same temp-file-
    in-destination-directory rename, same optional fsync pair — for
    artifacts that are text but not JSON (bounce-derived profile CSVs,
    :func:`bdlz_tpu.lz.profile.write_profile_csv`).  Readers see either
    the old complete file or the new complete file, never half a write.
    """
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(d)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_savez(path: str, durable: bool = False, **arrays: Any) -> None:
    """``np.savez`` with the mkstemp + ``os.replace`` atomicity of
    :func:`atomic_write_json`.

    THE array-write primitive for every resumable/loadable artifact
    (sweep chunk files, emulator tables, MCMC chain segments): a crash
    mid-``np.savez`` into the final path leaves a torn zip that resume
    must detect-and-recompute — atomic replacement means readers see
    either the old complete file or the new complete file, never half a
    write.  The temp name must end in ``.npz`` or ``np.savez`` APPENDS
    the suffix and the rename misses (the lesson already learned in
    ``emulator/artifact.py``).  ``durable`` adds the fsync pair of
    :func:`atomic_write_json` (file before the rename, directory after)
    so the entry survives host crash — the store's commit guarantee.
    """
    import numpy as np  # host-side IO only (bdlz-lint R1 audit)

    if not path.endswith(".npz"):
        path += ".npz"  # np.savez's suffix rule, kept for callers' sake
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(d)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_save_npy(path: str, arr: Any, durable: bool = False) -> None:
    """``np.save`` with the mkstemp + ``os.replace`` atomicity of its
    siblings above — the single-array primitive behind the provenance
    store and the accuracy-gate reference cache.  Writing through the
    open file descriptor sidesteps ``np.save``'s append-``.npy`` suffix
    rule, so the rename target is exactly ``path``.  ``durable`` adds
    the fsync pair (file before the rename, directory after).
    """
    import numpy as np  # host-side IO only (bdlz-lint R1 audit)

    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npy")
    try:
        with os.fdopen(fd, "wb") as f:
            np.save(f, arr)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(d)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _scalar(v: Any) -> Any:
    """Coerce numpy/jax scalars to plain Python types for JSON."""
    if hasattr(v, "item"):
        return v.item()
    return v


def yields_out_payload(cfg: Config, P_used: float, result: YieldsResult) -> Dict[str, Any]:
    inputs: Dict[str, Any] = {k: getattr(cfg, k) for k in REFERENCE_KEYS}
    inputs["P_used"] = _scalar(P_used)
    defaults = default_config()
    # every framework-extension field, in declaration order — derived from
    # the dataclass so new extensions are covered automatically
    for key in defaults:
        if key not in REFERENCE_KEYS and getattr(cfg, key) != defaults[key]:
            inputs[key] = getattr(cfg, key)
    return {
        "inputs": inputs,
        "final": {
            "Y_B": _scalar(result.Y_B),
            "Y_chi": _scalar(result.Y_chi),
            "rho_B_kg_m3": _scalar(result.rho_B_kg_m3),
            "rho_DM_kg_m3": _scalar(result.rho_DM_kg_m3),
            "DM_over_B": _scalar(result.DM_over_B),
        },
    }


def write_yields_out(path: str, cfg: Config, P_used: float, result: YieldsResult) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(yields_out_payload(cfg, P_used, result), f, indent=2)
