"""Accelerator-liveness guard for entry points.

This container reaches its TPU through a relay whose compile endpoint can
die independently of the chip; when it is down, *any* JAX backend touch
with the axon plugin registered hangs forever rather than erroring.  Every
CLI that is about to touch JAX therefore probes the socket first and pins
the host-CPU platform when the accelerator is unreachable — turning an
infinite hang into a loud, working fallback.  (The reference has no
accelerator at all, `first_principles_yields.py:19-28`; this is framework
plumbing for the failure-detection bullet of SURVEY §5.)
"""
from __future__ import annotations

import os
import socket
import sys

#: The axon relay's compile endpoint (host, port).
RELAY_ADDR = ("127.0.0.1", 8083)


def axon_relay_alive(timeout: float = 2.0) -> bool:
    """True if the TPU relay's compile endpoint accepts connections."""
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(RELAY_ADDR)
        return True
    except OSError:
        return False
    finally:
        s.close()


def axon_registered() -> bool:
    """True when the axon plugin will register in this process.

    ``PALLAS_AXON_POOL_IPS`` is what gates the sitecustomize plugin
    registration (it force-registers in every process and overrides
    ``JAX_PLATFORMS``), so it — not ``JAX_PLATFORMS`` — tells us whether a
    dead relay can hang the backend.
    """
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def ensure_live_backend(label: str = "bdlz", force_cpu: bool = False) -> bool:
    """Pin host CPU if the accelerator path would hang; return True if CPU.

    Must run before the first JAX backend touch (``jax.config.update`` is
    the only reliable override in this environment; env vars are read too
    early).  Returns whether the process ended up pinned to CPU.
    """
    if not force_cpu and axon_registered() and not axon_relay_alive():
        print(
            f"[{label}] accelerator relay unreachable; falling back to host CPU",
            file=sys.stderr,
        )
        force_cpu = True
    if force_cpu:
        import jax

        # backend.py itself depends on this guard (jax_numpy probes the
        # relay before the first backend touch), so the platform pin
        # cannot route through the backend helpers without a cycle.
        jax.config.update("jax_platforms", "cpu")  # bdlz-lint: disable=R5
    return force_cpu


def wait_for_relay(max_wait_s: float = 0.0, poll_s: float = 10.0) -> bool:
    """Poll the relay for up to ``max_wait_s`` seconds; True when alive.

    The relay is an environment state that can recover (observed: it has
    come back after dying); benches that *want* the TPU number can spend a
    bounded wait on it instead of silently downgrading the metric.
    """
    import time

    deadline = time.time() + max_wait_s
    while True:
        if axon_relay_alive():
            return True
        if time.time() >= deadline:
            return False
        time.sleep(min(poll_s, max(0.1, deadline - time.time())))
