"""Accelerator-liveness guard for entry points.

This container reaches its TPU through a relay whose compile endpoint can
die independently of the chip; when it is down, *any* JAX backend touch
with the axon plugin registered hangs forever rather than erroring.  Every
CLI that is about to touch JAX therefore probes the socket first and pins
the host-CPU platform when the accelerator is unreachable — turning an
infinite hang into a loud, working fallback.  (The reference has no
accelerator at all, `first_principles_yields.py:19-28`; this is framework
plumbing for the failure-detection bullet of SURVEY §5.)
"""
from __future__ import annotations

import os
import socket
import sys
import time

#: The axon relay's compile endpoint (host, port).
RELAY_ADDR = ("127.0.0.1", 8083)

#: Process-wide relay verdict memo: ``None`` until a probe (or a
#: completed bounded wait) resolves it, then the bool every later
#: caller reuses.  One process pays the relay wait at most ONCE —
#: BENCH_r05 stamped ``relay_waited_s: 600.0`` and then later legs'
#: backend touches re-probed (and on a flapping relay re-waited) for
#: the same dead endpoint.  A live verdict is also cached: the relay
#: serving this process's backend is not going to un-register mid-run,
#: and a 2 s TCP probe per CLI layer adds up.
_RELAY_VERDICT: "bool | None" = None


def reset_relay_cache() -> None:
    """Forget the cached relay verdict (tests; long-lived supervisors
    that want to re-admit a recovered relay)."""
    global _RELAY_VERDICT
    _RELAY_VERDICT = None


def _probe_relay(timeout: float) -> bool:
    """One uncached TCP probe of the relay's compile endpoint."""
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(RELAY_ADDR)
        return True
    except OSError:
        return False
    finally:
        s.close()


def axon_relay_alive(timeout: float = 2.0) -> bool:
    """True if the TPU relay's compile endpoint accepts connections.

    The verdict is cached per process after the first resolution (see
    ``_RELAY_VERDICT``); ``reset_relay_cache()`` forgets it.
    """
    global _RELAY_VERDICT
    if _RELAY_VERDICT is None:
        _RELAY_VERDICT = _probe_relay(timeout)
    return _RELAY_VERDICT


def axon_registered() -> bool:
    """True when the axon plugin will register in this process.

    ``PALLAS_AXON_POOL_IPS`` is what gates the sitecustomize plugin
    registration (it force-registers in every process and overrides
    ``JAX_PLATFORMS``), so it — not ``JAX_PLATFORMS`` — tells us whether a
    dead relay can hang the backend.
    """
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def ensure_live_backend(label: str = "bdlz", force_cpu: bool = False) -> bool:
    """Pin host CPU if the accelerator path would hang; return True if CPU.

    Must run before the first JAX backend touch (``jax.config.update`` is
    the only reliable override in this environment; env vars are read too
    early).  Returns whether the process ended up pinned to CPU.
    """
    if not force_cpu and axon_registered() and not axon_relay_alive():
        print(
            f"[{label}] accelerator relay unreachable; falling back to host CPU",
            file=sys.stderr,
        )
        force_cpu = True
    if force_cpu:
        import jax

        # backend.py itself depends on this guard (jax_numpy probes the
        # relay before the first backend touch), so the platform pin
        # cannot route through the backend helpers without a cycle.
        jax.config.update("jax_platforms", "cpu")  # bdlz-lint: disable=R5
    return force_cpu


def wait_for_relay(
    max_wait_s: float = 0.0, poll_s: float = 10.0, sleep=time.sleep
) -> bool:
    """Poll the relay for up to ``max_wait_s`` seconds; True when alive.

    The relay is an environment state that can recover (observed: it has
    come back after dying); benches that *want* the TPU number can spend a
    bounded wait on it instead of silently downgrading the metric.

    The wait is paid AT MOST ONCE per process: its outcome lands in the
    shared verdict cache, so a second ``wait_for_relay`` (or any
    ``axon_relay_alive`` / ``ensure_live_backend`` probe on a later
    bench leg) returns the cached verdict immediately — a round with a
    dead relay pays its ``relay_waited_s`` exactly once, not once per
    metric leg.

    ``sleep`` is the injectable-wait seam (bdlz-lint R7: all real
    blocking goes through an injectable sleep so tests never block);
    the default is a REFERENCE to ``time.sleep``, the sanctioned R7
    pattern — only bare calls are flagged.
    """
    global _RELAY_VERDICT
    if _RELAY_VERDICT is not None:
        return _RELAY_VERDICT
    deadline = time.time() + max_wait_s
    while True:
        if _probe_relay(2.0):
            _RELAY_VERDICT = True
            return True
        if time.time() >= deadline:
            _RELAY_VERDICT = False
            return False
        sleep(min(poll_s, max(0.1, deadline - time.time())))
