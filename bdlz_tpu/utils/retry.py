"""Bounded retry with deterministic backoff (the robustness layer's
shared primitive).

One home for the retry POLICY — attempts budget, backoff schedule,
deterministic jitter, injectable sleep — that the self-healing sweep
(``parallel/sweep.py``), the emulator's probe evaluator
(``emulator/build.py``), and the serve stack's exact-fallback isolation
(``serve/service.py``) all share, so their failure semantics cannot
drift apart.  The emulator and serve paths run the literal
:func:`call_with_retry` loop; the sweep's heal path drives its own
attempt loop (its bisect control flow interleaves with the attempts)
but takes every delay from :func:`backoff_delay`, so the schedule is
still this module's, everywhere:

* **bounded attempts** — a persistent failure always surfaces (to the
  caller's bisect/quarantine/error path), never an infinite loop;
* **deterministic jitter** — the backoff schedule is a pure function of
  ``(seed, label, attempt)`` (SHA-256 derived, no global RNG state), so
  multi-controller processes running the same retry plan sleep the same
  schedule and tests can pin exact delays;
* **injectable sleep** — tier-1 tests pass ``sleep=lambda s: None`` and
  never block (the same design rule as the serve batcher's injectable
  clock).

The ``retry_*`` config knobs resolve here (:func:`resolve_retry_policy`,
the ode_*/quad_* tri-state pattern): ``retry_enabled=None`` means
"engine decides" — the chunked/serving engines turn healing ON, the
bit-pinned per-point paths have no chunk loop and are unaffected —
while an explicit ``False`` restores raise-through for debugging.
"""
from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple, Type


class RetryPolicy(NamedTuple):
    """How a healing call site retries: attempts, backoff, sleep seam."""

    #: Total attempts (first try included); >= 1.  1 = no retry, the
    #: failure goes straight to the caller's bisect/quarantine path.
    max_attempts: int = 3
    #: Base backoff before the first retry; doubles per retry.
    backoff_s: float = 0.05
    #: Backoff ceiling (keeps the doubled schedule bounded).
    max_backoff_s: float = 2.0
    #: Seed of the deterministic jitter stream.
    seed: int = 0
    #: Injectable sleep — tests pass a no-op and never block.
    sleep: Callable[[float], None] = time.sleep


def deterministic_jitter(seed: int, label: str, attempt: int) -> float:
    """A reproducible uniform-ish value in [0, 1) from (seed, label, attempt).

    SHA-256 based so it is identical on every process and platform —
    multi-controller retry schedules must not diverge (``random`` module
    state or ``time``-seeded jitter would), and tests can pin delays.
    """
    digest = hashlib.sha256(f"{seed}:{label}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(2 ** 64)


def backoff_delay(policy: RetryPolicy, label: str, attempt: int) -> float:
    """Delay before retry ``attempt`` (0-based): capped exponential with
    deterministic half-to-full jitter (0.5–1.0× of the doubled base)."""
    base = float(policy.backoff_s) * (2.0 ** int(attempt))
    jitter = 0.5 + 0.5 * deterministic_jitter(policy.seed, label, attempt)
    return min(base * jitter, float(policy.max_backoff_s))


def call_with_retry(
    fn: Callable[[], Any],
    policy: RetryPolicy,
    label: str = "",
    retryable: "Tuple[Type[BaseException], ...]" = (Exception,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> Any:
    """Run ``fn`` under the policy; re-raise the last error when exhausted.

    ``on_retry(attempt, exc)`` fires before each retry's backoff sleep
    (attempt is 0-based over the retries, not the first try) — the hook
    call sites use to emit ``chunk_retry``-style events.
    """
    attempts = max(int(policy.max_attempts), 1)
    for attempt in range(attempts):
        try:
            return fn()
        except retryable as exc:  # noqa: PERF203 — the retry loop IS the point
            if attempt + 1 >= attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            policy.sleep(backoff_delay(policy, label, attempt))
    raise AssertionError("unreachable")  # pragma: no cover


def resolve_retry_policy(
    base=None,
    enabled: Optional[bool] = None,
    engine_default: bool = True,
    sleep: Optional[Callable[[float], None]] = None,
    seed: int = 0,
) -> Optional[RetryPolicy]:
    """Resolve the tri-state ``retry_enabled`` knob into a policy (or None).

    ``enabled`` overrides the config's ``retry_enabled`` when given
    (callers pass their StaticChoices value); ``None`` falls to
    ``engine_default`` — True in the chunked sweep / serve engines.
    Returns ``None`` when healing is OFF: call sites guard every hook on
    it, so the disabled path has zero overhead and byte-identical
    behavior.
    """
    attempts, backoff = 3, 0.05
    if base is not None:
        if enabled is None:
            enabled = getattr(base, "retry_enabled", None)
        attempts = int(getattr(base, "retry_max_attempts", attempts))
        backoff = float(getattr(base, "retry_backoff_s", backoff))
    on = engine_default if enabled is None else bool(enabled)
    if not on:
        return None
    return RetryPolicy(
        max_attempts=max(attempts, 1),
        backoff_s=backoff,
        seed=int(seed),
        sleep=time.sleep if sleep is None else sleep,
    )


def resolve_engine_retry(
    explicit: Optional[RetryPolicy],
    base,
    static=None,
    engine_default: bool = True,
) -> Optional[RetryPolicy]:
    """THE engine-level resolution: explicit policy ▸ static tri-state ▸
    config tri-state ▸ engine default.

    One home for the precedence chain the sweep engine, the emulator
    build, and the serve stack all apply — spelled once so a future
    precedence change cannot silently diverge between engines.
    """
    if explicit is not None:
        return explicit
    enabled = getattr(static, "retry_enabled", None) if static is not None else None
    if enabled is None:
        enabled = getattr(base, "retry_enabled", None)
    return resolve_retry_policy(
        base, enabled=enabled, engine_default=engine_default
    )
