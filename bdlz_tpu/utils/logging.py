"""Structured logging (SURVEY §5).

The reference logs via bare prints (:322-326, :419-422); the CLI keeps
those byte-compatible. Everything else in the framework emits structured
JSON-lines events through this module so sweeps/samplers are machine
observable.
"""
from __future__ import annotations

import json
import sys
import time
from typing import Any, IO, Optional


class EventLog:
    """JSON-lines event logger. One line per event: {ts, event, **fields}."""

    def __init__(self, stream: Optional[IO[str]] = None, path: Optional[str] = None):
        self._stream = stream
        self._path = path
        self._fh: Optional[IO[str]] = None

    def _out(self) -> IO[str]:
        if self._fh is None:
            if self._path is not None:
                self._fh = open(self._path, "a", encoding="utf-8")
            else:
                self._fh = self._stream or sys.stderr
        return self._fh

    def emit(self, event: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 3), "event": event, **fields}
        out = self._out()
        out.write(json.dumps(rec, default=str) + "\n")
        out.flush()

    def close(self) -> None:
        if self._fh is not None and self._path is not None:
            self._fh.close()
            self._fh = None
