"""Utility layer: structured output, logging, manifests."""
