"""Tracing / profiling utilities (SURVEY §5: none in the reference —
print-statements only; here: jax.profiler traces + throughput reporting).
"""
from __future__ import annotations

import contextlib


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """Wrap a region in a jax.profiler trace (viewable in TensorBoard /
    xprof). No-op when trace_dir is None."""
    if trace_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def enable_nan_debugging(enable: bool = True) -> None:
    """NaN-checking mode — the numerical analog of a sanitizer (SURVEY §5):
    the reference papers over edge cases with floors (1e-30…1e-300); this
    makes any NaN produced under jit raise with a traceback instead."""
    from bdlz_tpu.backend import set_debug_nans

    set_debug_nans(enable)
