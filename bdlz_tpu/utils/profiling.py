"""Tracing / profiling utilities (SURVEY §5: none in the reference —
print-statements only; here: jax.profiler traces + throughput reporting).
"""
from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """Wrap a region in a jax.profiler trace (viewable in TensorBoard /
    xprof). No-op when trace_dir is None."""
    if trace_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


@dataclass
class Throughput:
    """Simple wall-clock throughput meter for sweep blocks."""

    n_items: int = 0
    seconds: float = 0.0
    _t0: float | None = None

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.seconds += time.time() - self._t0
        self._t0 = None

    def add(self, n: int) -> None:
        self.n_items += n

    @property
    def per_sec(self) -> float:
        return self.n_items / max(self.seconds, 1e-9)


def enable_nan_debugging(enable: bool = True) -> None:
    """NaN-checking mode — the numerical analog of a sanitizer (SURVEY §5):
    the reference papers over edge cases with floors (1e-30…1e-300); this
    makes any NaN produced under jit raise with a traceback instead."""
    import jax

    jax.config.update("jax_debug_nans", enable)
