"""Tracing / profiling utilities (SURVEY §5: none in the reference —
print-statements only; here: jax.profiler traces, throughput reporting,
and the stiff batch engine's per-round compaction counters).
"""
from __future__ import annotations

import contextlib
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple


@dataclass(frozen=True)
class EsdirkRound:
    """One round of the lane-repacking batched ESDIRK engine
    (``solvers/batching.py``): which lanes ran, what they did, how long
    the round took on the wall."""

    round_index: int
    batch_lanes: int       # padded batch actually dispatched
    active_lanes: int      # live (unconverged, in-budget) lanes this round
    lanes_retired: int     # lanes that finished (or exhausted) this round
    steps_accepted: int    # accepted steps across live lanes this round
    steps_rejected: int    # rejected attempts across live lanes this round
    seconds: float


@dataclass
class CompactionStats:
    """Per-round record of a repacked batched stiff solve.

    The engine appends one :class:`EsdirkRound` per dispatch; ``summary``
    collapses the list into the totals that bench JSON / event logs
    carry.  ``pad_waste`` is the fraction of dispatched lane-rounds that
    were padding or already-converged masking — the quantity the
    repacking exists to minimize (a lockstep solve of the same batch has
    waste = 1 − mean(steps)/max(steps) instead).
    """

    rounds: List[EsdirkRound] = field(default_factory=list)

    def record_round(self, **kw: Any) -> None:
        self.rounds.append(EsdirkRound(**kw))

    @property
    def n_rounds(self) -> int:
        return len(self.rounds)

    def summary(self) -> Dict[str, Any]:
        dispatched = sum(r.batch_lanes for r in self.rounds)
        active = sum(r.active_lanes for r in self.rounds)
        return {
            "rounds": self.n_rounds,
            "lanes_retired": sum(r.lanes_retired for r in self.rounds),
            "steps_accepted": sum(r.steps_accepted for r in self.rounds),
            "steps_rejected": sum(r.steps_rejected for r in self.rounds),
            "seconds": round(sum(r.seconds for r in self.rounds), 4),
            "pad_waste": round(1.0 - active / dispatched, 4) if dispatched else 0.0,
        }

    def as_rows(self) -> List[Dict[str, Any]]:
        """The per-round records as plain dicts (event logs, JSON)."""
        return [dataclasses.asdict(r) for r in self.rounds]


@dataclass(frozen=True)
class ServeBatch:
    """One dispatched micro-batch of the query service
    (``bdlz_tpu/serve``): how full it ran, how long its oldest request
    waited, how many requests missed the emulator domain and took the
    exact-pipeline fallback, and how long the evaluation took."""

    batch_index: int
    size: int              # requests in the batch
    occupancy: float       # size / max_batch_size
    wait_s: float          # oldest request's queue wait at dispatch
    n_fallback: int        # exact-pipeline requests (OOD + error-gated)
    seconds: float         # evaluation wall time
    # degraded-mode accounting (docs/robustness.md): exact-fallback
    # retries paid, and requests answered with a per-request error after
    # the retry budget (the serve analog of sweep quarantine)
    n_retries: int = 0
    n_error: int = 0
    #: The subset of ``n_fallback`` routed to the exact path by the
    #: PREDICTED-ERROR gate (reason "predicted_error") rather than by
    #: domain membership (reason "ood") — telemetry must distinguish a
    #: box that no longer covers the traffic from a surface that covers
    #: it but is not accurate enough where the traffic lands.
    n_gated: int = 0
    # fleet provenance (docs/serving.md): which artifact answered the
    # batch and which device replica ran it.  Every request in one batch
    # shares one artifact by construction — the rollout tests pin that a
    # cutover never mixes surfaces within a dispatch.
    artifact_hash: "str | None" = None
    replica: "int | None" = None
    #: The LZ physics scenario the answering artifact serves
    #: ("two_channel" | "chain" | "thermal"; docs/scenarios.md) — every
    #: service-recorded row names its mode so cross-mode traffic audits
    #: read straight off the stats.  None only on rows recorded by a
    #: bare MicroBatcher with no service behind it.
    lz_mode: "str | None" = None
    #: The fabric host that dispatched the batch (docs/serving.md,
    #: cross-host fabric) — cross-host traces must be attributable to
    #: the host that answered.  None on single-host services (the
    #: pre-fabric row schema, extended in place, never forked).
    host_id: "str | None" = None


@dataclass
class ServeStats:
    """Per-batch record of a serving session (same shape as
    :class:`CompactionStats`: record rows, collapse to a summary for
    bench JSON / event logs).  ``occupancy`` is the quantity dynamic
    batching exists to maximize; ``fallback_rate`` is the fraction of
    traffic the emulator could not absorb — a rising rate means the
    artifact's box no longer covers the query distribution.

    Every rate/percentile field of :meth:`summary` is ``None`` — never
    NaN, never a fabricated 0.0 — when its window is empty (zero batches
    dispatched, every request shed): a dashboard must be able to tell
    "nothing measured" from "measured zero", and the summary must stay
    ``json.dumps(..., allow_nan=False)``-safe under total overload.
    """

    rows: List[ServeBatch] = field(default_factory=list)
    #: Requests answered with ``DeadlineExceeded`` at dispatch instead of
    #: aging their batch (counted here, not per row — a fully-expired
    #: dispatch records no batch row at all).
    deadline_kills: int = 0
    #: Requests rejected at submit by admission control (bounded queue,
    #: ``serve.QueueFull``) — they never entered the queue at all.
    admission_rejects: int = 0
    #: Requests the queue accepted (admission's complement: offered
    #: traffic = accepted + admission_rejects).
    accepted: int = 0
    #: Per-request submit→resolve latencies on the service's clock (the
    #: fleet records one entry per answered request; percentile source).
    latencies_s: List[float] = field(default_factory=list)
    #: Seconds spent pre-compiling query kernels (artifact load + rollout
    #: warm-up) — the compile spike the warm start keeps out of p99.
    warmup_seconds: float = 0.0
    #: Opt-in summary extensions (the replica health plane, rollout
    #: auto-rollback records).  Keys land verbatim at the END of
    #: :meth:`summary`; EMPTY by default so the summary schema is
    #: byte-identical to the pre-health service whenever nothing armed
    #: them (the zero-overhead pin in tests/test_health.py).
    extras: Dict[str, Any] = field(default_factory=dict)
    #: Opt-in per-query traffic trace (the closed-loop refinement
    #: daemon's input, bdlz_tpu/refine): one ``(theta tuple, reason)``
    #: entry per answered request, ``reason`` as on the response
    #: (None = emulator fast path).  ``None`` — the default — disables
    #: recording entirely: :meth:`record_queries` is a no-op, rows and
    #: :meth:`summary` are byte-identical to an unarmed service (the
    #: zero-overhead pin in tests/test_refine.py).  Arm with
    #: :meth:`arm_traffic_log`.
    traffic_log: "List[Tuple[Tuple[float, ...], 'str | None']] | None" = None

    def arm_traffic_log(self) -> None:
        """Start recording per-query locations + fallback reasons."""
        if self.traffic_log is None:
            self.traffic_log = []

    def record_queries(self, thetas: Any, reasons: Any = None) -> None:
        """Append one entry per request of a resolved batch (no-op
        unless :meth:`arm_traffic_log` ran).  ``thetas`` is the (B, d)
        query block; ``reasons`` the per-request fallback reasons (a
        single string broadcasts; None = all emulator-answered)."""
        if self.traffic_log is None:
            return
        import numpy as np  # host-side stats (bdlz-lint R1 audit)

        block = np.atleast_2d(np.asarray(thetas, dtype=np.float64))
        b = block.shape[0]
        if reasons is None:
            reasons = [None] * b
        elif isinstance(reasons, str):
            reasons = [reasons] * b
        for row, reason in zip(block, reasons):
            self.traffic_log.append((tuple(float(v) for v in row), reason))

    def record_batch(self, **kw: Any) -> None:
        self.rows.append(ServeBatch(**kw))

    def record_deadline_kills(self, n: int) -> None:
        self.deadline_kills += int(n)

    def record_admission_rejects(self, n: int = 1) -> None:
        self.admission_rejects += int(n)

    def record_accepted(self, n: int = 1) -> None:
        self.accepted += int(n)

    def record_latency(self, seconds: float) -> None:
        self.latencies_s.append(float(seconds))

    def record_warmup(self, seconds: float) -> None:
        self.warmup_seconds += float(seconds)

    @property
    def n_batches(self) -> int:
        return len(self.rows)

    def _percentile(self, q: float) -> "float | None":
        if not self.latencies_s:
            return None
        import numpy as np  # host-side stats (bdlz-lint R1 audit)

        return round(float(np.percentile(np.asarray(self.latencies_s), q)), 6)

    def summary(self) -> Dict[str, Any]:
        requests = sum(r.size for r in self.rows)
        fallbacks = sum(r.n_fallback for r in self.rows)
        gated = sum(r.n_gated for r in self.rows)
        errors = sum(r.n_error for r in self.rows)
        shed = self.deadline_kills + self.admission_rejects
        offered = self.accepted + self.admission_rejects
        return {
            "batches": self.n_batches,
            "requests": requests,
            "fallbacks": fallbacks,
            "fallback_rate": (
                round(fallbacks / requests, 4) if requests else None
            ),
            # predicted-error-gated subset of the fallbacks ("ood" vs
            # "predicted_error" reasons — geometry misses vs accuracy
            # gating are different capacity-planning signals)
            "gated_fallbacks": gated,
            "gated_rate": (
                round(gated / requests, 4) if requests else None
            ),
            "mean_batch": (
                round(requests / self.n_batches, 2) if self.rows else None
            ),
            "mean_occupancy": (
                round(sum(r.occupancy for r in self.rows) / self.n_batches, 4)
                if self.rows else None
            ),
            "max_wait_s": (
                round(max(r.wait_s for r in self.rows), 6)
                if self.rows else None
            ),
            "seconds": round(sum(r.seconds for r in self.rows), 4),
            # degraded-mode accounting: how hard the service had to fight
            # (retries), what it shed (deadline kills), and what it could
            # not save (per-request errors = the serve quarantine rate)
            "retries": sum(r.n_retries for r in self.rows),
            "deadline_kills": self.deadline_kills,
            "errors": errors,
            "quarantine_rate": (
                round(errors / requests, 4) if requests else None
            ),
            # fleet-plane accounting (docs/serving.md): offered traffic
            # vs what overload control turned away, and the latency
            # percentiles of what was answered
            "accepted": self.accepted,
            "admission_rejects": self.admission_rejects,
            "shed_rate": round(shed / offered, 4) if offered else None,
            "p50_latency_s": self._percentile(50.0),
            "p99_latency_s": self._percentile(99.0),
            "warmup_seconds": round(self.warmup_seconds, 4),
            # health plane / auto-rollback extensions — absent entirely
            # when nothing armed them (schema pin)
            **self.extras,
        }

    def as_rows(self) -> List[Dict[str, Any]]:
        """The per-batch records as plain dicts (event logs, JSON)."""
        return [dataclasses.asdict(r) for r in self.rows]


@contextlib.contextmanager
def trace(trace_dir: str | None):
    """Wrap a region in a jax.profiler trace (viewable in TensorBoard /
    xprof). No-op when trace_dir is None."""
    if trace_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def enable_nan_debugging(enable: bool = True) -> None:
    """NaN-checking mode — the numerical analog of a sanitizer (SURVEY §5):
    the reference papers over edge cases with floors (1e-30…1e-300); this
    makes any NaN produced under jit raise with a traceback instead."""
    from bdlz_tpu.backend import set_debug_nans

    set_debug_nans(enable)
