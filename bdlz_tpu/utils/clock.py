"""Injectable clocks (bdlz-lint R7) — the single home.

``ManualClock``/``WallClock`` grew up inside the elastic scheduler
(``parallel/scheduler.py``) and were shadowed by ad-hoc fake-clock twins
in the serve tests; the cross-host fabric needs the same pair on the
serving side, so they live here and the old homes re-export.  Every
layer that waits (lease TTLs, autoscale intervals, host heartbeats)
takes one of these — tier-1 tests never sleep.
"""
from __future__ import annotations

import time


class ManualClock:
    """Deterministic injectable clock for in-process drivers/tests:
    time only moves when :meth:`advance` is called, so lease TTLs expire
    exactly at scripted round boundaries and tier-1 never sleeps."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def advance(self, seconds: float) -> float:
        self._now += float(seconds)
        return self._now


class WallClock:
    """Real-time clock for driving in-process control loops alongside
    EXTERNAL worker processes (``sweep_cli --elastic coordinator``, the
    multi-process serving fabric): ``now`` is wall time and
    :meth:`advance` actually waits, so the driver's lease arithmetic
    agrees with workers using ``time.time``.  Both seams are injectable
    — ``sleep=time.sleep`` here is a default-arg REFERENCE, the
    sanctioned bdlz-lint R7 pattern."""

    def __init__(self, time_fn=time.time, sleep=time.sleep):
        self._time = time_fn
        self._sleep = sleep

    def __call__(self) -> float:
        return float(self._time())

    def advance(self, seconds: float) -> float:
        self._sleep(float(seconds))
        return float(self._time())
