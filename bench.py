#!/usr/bin/env python3
"""Benchmark: parameter-sweep throughput of the TPU yields pipeline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: parameter-grid points/sec through the full flagship pipeline
(PointParams → Y_B quadrature → present-day Ω ratio) using the tabulated
KJMA fast path on a 4-D (m_χ, T_p, P, v_w) grid, batch sharded over all
local devices. Baseline: the measured reference throughput of 4.3
points/sec/core (BASELINE.md — SciPy pipeline, single CPU core), so
``vs_baseline`` is the speedup over the reference implementation.

Accuracy gate: before timing, the benched engine runs a ~128-config
adversarial population (broad/deep-MB/clip-edge/seam classes — the same
builder behind ACCURACY_AUDIT.json, bdlz_tpu.validation) plus a small
in-grid chunk-integrity sample, both against the bit-reproducible NumPy
reference path; the max relative error on Ω_DM/Ω_b is reported in the
JSON line and must stay ≤1e-6 (north-star contract).
BDLZ_BENCH_GATE_POINTS sizes the population (default 128).

Env knobs: BDLZ_BENCH_POINTS (default 262144), BDLZ_BENCH_CHUNK (default
8192 per device — sized so the (chunk × n_y) integrand temporaries fit a
single v5e chip's 16G HBM), BDLZ_BENCH_NY (default 8000),
BDLZ_BENCH_IMPL=pallas|tabulated (default: pallas on TPU — the MXU
interpolation kernel in ops/kjma_pallas.py, with automatic fallback if it
fails the gate — tabulated on CPU), BDLZ_BENCH_QUAD=auto|on|off (default
auto — the tabulated engine's y-quadrature: snapped-panel Gauss–Legendre
(solvers/panels.py, ~14x less integrand work) when the per-population
convergence audit passes on the bench grid, else the reference
trapezoid; an A/B sub-metric line "quad_gl_sweep_points_per_sec_per_chip"
records the measured vs_trapezoid speedup and the panel path's gate
error every round), BDLZ_BENCH_QUAD_POINTS (A/B subset size),
BDLZ_BENCH_PLATFORM=cpu to force the host platform (debug only),
BDLZ_RELAY_WAIT_S / --relay-wait (how long to wait for a dead
accelerator relay to recover before benching CPU: flag > BDLZ_RELAY_WAIT_S
> legacy BDLZ_BENCH_RELAY_WAIT_S > default — 60 s when JAX_PLATFORMS=cpu
says this process never wanted the accelerator, 600 s otherwise; the
JSON stamps platform/tpu_unavailable/relay_waited_s either way),
BDLZ_BENCH_STIFF_POINTS (grid size for the secondary stiff ESDIRK
sweep metric, printed as its own line before the main one; PINNED at
1024 on every platform so rounds are comparable — BENCH_r02's 1024-pt
and r05's 64-pt numbers were not; the legacy BDLZ_BENCH_ODE_POINTS
name still works — the line records engine + n_points and A/Bs the
lane-repacking batch engine against the legacy lockstep strategy:
vs_lockstep, both engines' Radau spot accuracy, and the per-round
compaction stats), BDLZ_BENCH_LZ_POINTS (grid size for
the two LZ-sweep secondary metrics — per-point P derived from a bounce
profile through the two-channel LZ kernel, once analytically and once
through the coherent transfer-matrix P(v_w) table; default: the full
grid on TPU, 4096 on CPU fallback), BDLZ_BENCH_LZ_TABLE_N (coherent
P-table nodes; default 16384 on TPU, 2048 on CPU fallback),
BDLZ_BENCH_BOUNCE_POINTS (spec-batch size for the
bounce_sweep leg — potentials/sec/chip through the batched O(4)
shooting solver with the host scalar-loop A/B and the validation-gate
residuals on the line; default 8, one full lane),
BDLZ_BENCH_SERVE_QUERIES / BDLZ_BENCH_SERVE_BATCH /
BDLZ_BENCH_SERVE_REPLICAS / BDLZ_BENCH_SERVE_LAT_QUERIES (the
serve_bench leg: request-stream size, micro-batch bucket, fleet size,
and the closed-loop latency sample — the leg replays the round's
emulator artifact through the per-device replica fleet and reports
QPS/chip, replica scaling, p50/p99 latency, and the deterministic shed
rate of a canned overload trace), BDLZ_BENCH_SEAM_NY /
BDLZ_BENCH_SEAM_RTOL / BDLZ_BENCH_SEAM_ROUNDS /
BDLZ_BENCH_SEAM_QUERIES / BDLZ_BENCH_SEAM_EXACT (the seam_split leg:
an A/B seam-crossing emulator box built split-domain vs single-domain
at equal tolerance, then a deterministic seam-crossing query trace
through the predicted-error-gated service — exact-fallback ratio,
gated/ungated rates and effective QPS for both artifacts, and the
gated answers spot-checked against the exact engine, all on one
line), BDLZ_BENCH_SI_QUERIES / BDLZ_BENCH_SI_BATCH / BDLZ_BENCH_SI_NY
(the self_improve leg: per-hour request count, micro-batch bucket, and
rebuild table resolution for the closed-loop self-improving service —
a two-hour drifted trace on a fake clock through the refinement
daemon's detect → traffic-steered rebuild → auto-publish cycle,
reporting hour-1 vs hour-2 gated-fallback rates and the
unaffected-region bitwise pin).  Every secondary leg runs on EVERY
platform (flagged tpu_unavailable on the fallback path) so a
relay-dead round still records full engine coverage.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _relay_wait_default() -> float:
    """Bounded relay wait: flag > BDLZ_RELAY_WAIT_S > legacy env > default.

    The default is 60 s when ``JAX_PLATFORMS=cpu`` — a process that has
    already pinned the host platform only reaches the wait through the
    axon plugin's force-registration, and burning the old 600 s default
    there stalls every CPU-pinned round for ten minutes before producing
    the exact same flagged CPU number (BENCH_r05: relay_waited_s=600.0).
    """
    for env in ("BDLZ_RELAY_WAIT_S", "BDLZ_BENCH_RELAY_WAIT_S"):
        raw = os.environ.get(env)
        if raw:
            return float(raw)
    return 60.0 if os.environ.get("JAX_PLATFORMS") == "cpu" else 600.0


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="bdlz_tpu sweep benchmark")
    ap.add_argument(
        "--relay-wait", type=float, default=None, dest="relay_wait",
        help="Seconds to wait for a dead accelerator relay before "
             "benching host CPU (default: BDLZ_RELAY_WAIT_S, else the "
             "legacy BDLZ_BENCH_RELAY_WAIT_S, else 60 when "
             "JAX_PLATFORMS=cpu / 600 otherwise)",
    )
    args = ap.parse_args(argv)

    from bdlz_tpu.utils.platform import axon_registered, wait_for_relay

    force_cpu = os.environ.get("BDLZ_BENCH_PLATFORM") == "cpu"
    tpu_unavailable = False
    relay_waited = 0.0
    # PALLAS_AXON_POOL_IPS is what gates the sitecustomize axon-plugin
    # registration (it force-registers in every process and overrides
    # JAX_PLATFORMS), so it — not JAX_PLATFORMS — tells us whether a dead
    # relay can hang the backend.  A dead relay is an environment state
    # that can recover (observed), so the bench *waits* for it (bounded)
    # instead of silently downgrading the round's metric to a CPU number.
    if not force_cpu and axon_registered():
        max_wait = (
            args.relay_wait if args.relay_wait is not None
            else _relay_wait_default()
        )
        t_wait = time.time()
        alive = wait_for_relay(max_wait_s=max_wait, poll_s=15.0)
        relay_waited = round(time.time() - t_wait, 1)
        if not alive:
            print(
                f"[bench] accelerator relay unreachable after waiting "
                f"{relay_waited}s; benching host CPU — this is NOT a TPU "
                "number (tpu_unavailable=true in the JSON)",
                file=sys.stderr,
            )
            force_cpu = True
            tpu_unavailable = True
    if force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.models.yields_pipeline import point_yields
    # imported up-front so a typo'd BDLZ_PALLAS_COL_BLOCK fails fast,
    # before the (minutes-long) timed sweep rather than after it
    from bdlz_tpu.ops.kjma_pallas import pallas_evidence_row
    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.parallel.mesh import batch_sharding, make_mesh
    from bdlz_tpu.parallel.sweep import build_grid, _pad_chunk
    from bdlz_tpu.physics.percolation import make_kjma_grid

    n_points = int(os.environ.get("BDLZ_BENCH_POINTS", 262144))
    n_y = int(os.environ.get("BDLZ_BENCH_NY", 8000))

    devices = jax.devices()
    n_dev = len(devices)

    # ---- bench-leg result cache (docs/provenance.md, opportunistic
    # benching) -------------------------------------------------------
    # On a tpu_unavailable round every leg is a flagged CPU number —
    # deterministic per (code, BDLZ_* knobs, platform) and worth many
    # minutes per round (BENCH_r03–r05 re-paid the full CPU suite after
    # every relay death).  Those legs are keyed by provenance identity
    # (bench_leg_identity: leg name + env snapshot + a source
    # fingerprint, so ANY code change re-measures) and replayed with
    # ``"cached": true`` on each reused metric line; when the relay
    # returns, the round runs on hardware and never consults the cache
    # — only the CPU legs are reused, only while they are still
    # evidence for this exact build.  BDLZ_BENCH_LEG_CACHE=0 disables.
    _capture_stack: list = []

    def emit(payload) -> None:
        """Print one metric JSON line (and record it for leg caching)."""
        print(json.dumps(payload))
        for buf in _capture_stack:
            buf.append(payload)

    leg_store = None
    leg_ctx = None
    _leg_cache_on = (
        tpu_unavailable and os.environ.get("BDLZ_BENCH_LEG_CACHE", "1") != "0"
    ) or os.environ.get("BDLZ_BENCH_LEG_CACHE") == "force"  # tests only
    if _leg_cache_on:
        from bdlz_tpu.provenance import (
            Store,
            StoreUntrustedError,
            default_store_root,
            package_source_fingerprint,
        )

        try:
            leg_store = Store(
                os.environ.get("BDLZ_CACHE_ROOT") or default_store_root()
            )
        except StoreUntrustedError as exc:
            print(f"[bench] leg cache disabled: {exc}", file=sys.stderr)
        if leg_store is not None:
            leg_ctx = {
                "platform": jax.devices()[0].platform,
                "n_dev": n_dev,
                "env": {
                    k: v for k, v in sorted(os.environ.items())
                    if k.startswith("BDLZ_") and k != "BDLZ_CACHE_ROOT"
                },
                "fingerprint": package_source_fingerprint(
                    os.path.abspath(__file__)
                ),
            }

    def _leg_entry_name(leg: str) -> str:
        from bdlz_tpu.provenance import bench_leg_identity

        return f"bench_leg/{bench_leg_identity(leg, leg_ctx).digest(24)}.json"

    def leg_lookup(leg: str):
        """Replay a cached leg's metric lines (``cached: true``); the
        stored ``{"lines", "summary"}`` entry, or None on miss."""
        if leg_store is None:
            return None
        ent = leg_store.get_json(_leg_entry_name(leg))
        if not isinstance(ent, dict) or "lines" not in ent:
            return None
        print(
            f"[bench] {leg}: reusing the cached CPU measurement (relay "
            "down; a code or BDLZ_* knob change re-measures)",
            file=sys.stderr,
        )
        for line in ent["lines"]:
            emit({**line, "cached": True})
        return ent

    def leg_record(leg: str, lines, summary) -> None:
        if leg_store is not None:
            leg_store.put_json(
                _leg_entry_name(leg), {"lines": lines, "summary": summary}
            )

    def run_leg(leg: str, fn):
        """One cacheable bench leg: replay on hit; capture, run, and
        record on miss.  A leg that raises is never recorded (it should
        re-attempt next round), and the exception propagates to the
        caller's best-effort handler."""
        hit = leg_lookup(leg)
        if hit is not None:
            return hit.get("summary")
        buf: list = []
        _capture_stack.append(buf)
        try:
            summary = fn()
        finally:
            _capture_stack.pop()
        leg_record(leg, buf, summary)
        return summary

    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)

    # 4-D grid around the archived benchmark point (BASELINE.json configs).
    side = max(2, int(round(n_points ** 0.25)))
    axes = {
        "m_chi_GeV": np.geomspace(0.1, 10.0, side),
        "T_p_GeV": np.geomspace(30.0, 300.0, side),
        "P_chi_to_B": np.linspace(0.02, 0.9, side),
        "v_w": np.linspace(0.05, 0.9, side),
    }
    pp_all = build_grid(base, axes)
    n_total = int(np.asarray(pp_all.m_chi_GeV).shape[0])

    # Per-device chunk: the fused integrand lives as (chunk/n_dev × n_y)
    # f64 temporaries; 8192 points/device × 8000 nodes fits a 16G-HBM v5e
    # chip. Capped at the (device-rounded) grid size so large slices don't
    # pad every launch and skew the reported per-chip throughput.
    chunk = int(
        os.environ.get(
            "BDLZ_BENCH_CHUNK",
            min(8192 * n_dev, ((n_total + n_dev - 1) // n_dev) * n_dev),
        )
    )
    chunk = ((chunk + n_dev - 1) // n_dev) * n_dev

    mesh = make_mesh(shape=(n_dev, 1))
    sharding = batch_sharding(mesh)
    # host-built table once; the jnp copy ships the same bytes (the
    # audit below and the engines must share one table identity)
    from bdlz_tpu.ops.kjma_table import table_to_namespace

    table_np = make_f_table(base.I_p, np)
    table = table_to_namespace(table_np, jnp)

    # --- y-quadrature resolution (the tabulated engine's tri-state) ----
    # BDLZ_BENCH_QUAD=auto runs the SHARED resolver (the same audit +
    # announcement run_sweep and the emulator build use) over the bench
    # grid; the snapped-panel Gauss-Legendre fast path only defaults on
    # when the audit passes, else the bench stays on the reference
    # trapezoid loudly.  "on"/"off" pin it.
    from bdlz_tpu.solvers.panels import (
        N_PANELS_DEFAULT,
        NODES_PER_PANEL_DEFAULT,
    )
    from bdlz_tpu.validation import resolve_quad_panel_gl

    quad_mode = os.environ.get("BDLZ_BENCH_QUAD", "auto")
    quad_audit = None
    if quad_mode == "auto":
        quad_on, quad_audit = resolve_quad_panel_gl(
            pp_all, static, "tabulated", n_y, table=table_np,
            label="bench",
        )
    else:
        quad_on = quad_mode == "on"
    n_quad_gl = N_PANELS_DEFAULT * NODES_PER_PANEL_DEFAULT
    # `static` keeps the config tri-state (None -> trapezoid on every
    # bit-pinned path, incl. the gate references); `static_gl` is the
    # panel scheme.  Every gate below compares an engine against the
    # NumPy reference run at the engine's OWN scheme (the established
    # equal-discretization rule).
    static_gl = static._replace(quad_panel_gl=True)

    def static_for(impl_: str):
        """The static (incl. resolved quadrature) an engine runs with."""
        return static_gl if (impl_ == "tabulated" and quad_on) else static

    def make_run_chunk(impl: str, reduce=None, pp=None, static_run=None):
        # shared engine-runner (pallas aux pairing, interpret-on-CPU,
        # memory clamp, pad + shard + evaluate) —
        # bdlz_tpu.parallel.sweep.make_chunk_runner, also used by
        # scripts/impl_shootout.py so the two tools measure the same
        # thing; ``pp`` defaults to the bench grid (the LZ metric passes
        # its P-derived variant), ``static_run`` to the engine's
        # resolved-quadrature static
        nonlocal chunk
        from bdlz_tpu.parallel.sweep import make_chunk_runner

        fuse = os.environ.get("BDLZ_BENCH_FUSE_EXP", "0") == "1"
        run_chunk, chunk = make_chunk_runner(
            pp_all if pp is None else pp, chunk,
            static_for(impl) if static_run is None else static_run,
            mesh, sharding,
            table, impl=impl, n_y=n_y, fuse_exp=fuse, reduce=reduce,
        )
        return run_chunk

    def accuracy_gate(run_chunk, pp=None, static_run=None):
        """Max rel err of a point sample vs the NumPy reference path.

        The first chunk evaluation doubles as compile warm-up; any
        compile/runtime failure propagates to the caller for fallback.
        ``pp`` must be the grid ``run_chunk`` was built over (default:
        the bench grid) and ``static_run`` the static it runs with —
        the reference is evaluated at the SAME static (same n_y, same
        quadrature scheme), so the gate measures backend drift, not
        scheme differences.  Sampled indices are grouped by chunk and
        each needed chunk is evaluated ONCE (VERDICT r4 weak #5 — the
        old per-index loop re-ran a full chunk per sampled corner).
        """
        pp = pp_all if pp is None else pp
        static_run = static if static_run is None else static_run
        n_pts = int(np.asarray(pp.m_chi_GeV).shape[0])
        rng = np.random.default_rng(0)
        sample = rng.choice(n_pts, size=min(8, n_pts), replace=False)
        # Deliberate corners beyond the random draw: the grid's flat-index
        # extremes, the deepest Maxwell-Boltzmann point (max m/T_p), the
        # most relativistic one (min m/T_p), and the point whose T = m/3
        # branch seam sits closest to the percolation temperature — the
        # hard n_eq/vbar discontinuity the 1e-6 contract must survive.
        m = np.asarray(pp.m_chi_GeV)
        Tp = np.asarray(pp.T_p_GeV)
        corners = np.array([
            0, n_pts - 1,
            int(np.argmax(m / Tp)), int(np.argmin(m / Tp)),
            int(np.argmin(np.abs(3.0 * Tp - m))),
        ])
        sample = np.unique(np.concatenate([sample, corners]))
        grid_np = make_kjma_grid(np)
        # equal-discretization reference (same n_y as the benched engine)
        static_gate = (
            static_run._replace(n_y=n_y) if static_run.n_y != n_y
            else static_run
        )
        max_rel = 0.0
        # chunk 0 always runs (compile warm-up contract), then one
        # evaluation per chunk that holds a sampled index
        for lo_c in sorted({0, *((i // chunk) * chunk for i in sample)}):
            vals = np.asarray(run_chunk(lo_c, min(lo_c + chunk, n_pts)))
            for i in sample[(sample >= lo_c) & (sample < lo_c + chunk)]:
                pp_i = type(pp)(*(float(np.asarray(f)[i]) for f in pp))
                ref = float(
                    point_yields(pp_i, static_gate, grid_np, np).DM_over_B
                )
                if ref != 0.0:
                    max_rel = max(max_rel, abs(float(vals[i - lo_c]) / ref - 1.0))
        return max_rel

    # ~128-config adversarial population for the gate (VERDICT r3 weak
    # #7: the thin in-grid sample becomes the chunk-integrity check; the
    # contract gate is this audit-style population — broad/deep-MB/
    # clip-edge/seam classes from bdlz_tpu.validation, the same builder
    # behind ACCURACY_AUDIT.json). Reference ratios computed once and
    # shared across engine attempts (pallas try + fallback).
    from bdlz_tpu.validation import (
        build_audit_population,
        reference_ratios_cached,
    )

    n_gate = int(os.environ.get("BDLZ_BENCH_GATE_POINTS", 128))
    gate_pop = build_audit_population(base, n_gate, seed=1)
    # cached: bit-deterministic, and the collector's phases share one
    # hardware window — don't re-pay the scalar reference loop per tool.
    # One reference per SCHEME (trap/panel-GL), computed lazily: the
    # gate always compares an engine against the NumPy reference at the
    # engine's own quadrature (equal-scheme rule — the trapezoid
    # reference is O(h)-wrong at the population's T=m/3 seam corners,
    # so cross-scheme comparison would measure the reference's error).
    _gate_refs: dict = {}

    def gate_ref_for(st):
        key = bool(st.quad_panel_gl)
        if key not in _gate_refs:
            _gate_refs[key] = reference_ratios_cached(
                gate_pop.grid, st, n_y=n_y
            )
        return _gate_refs[key]

    def population_gate(impl: str, reduce=None, static_run=None) -> float:
        """Max rel err of the benched engine over the audit population.

        Raises ``validation.GateFailure`` on non-finite engine output
        (runner construction + loop shared with the shootout)."""
        from bdlz_tpu.validation import engine_population_max_rel

        fuse = os.environ.get("BDLZ_BENCH_FUSE_EXP", "0") == "1"
        static_run = static_for(impl) if static_run is None else static_run
        return engine_population_max_rel(
            gate_pop.grid, gate_ref_for(static_run), static_run, mesh,
            sharding, table,
            impl=impl, n_y=n_y, fuse_exp=fuse, reduce=reduce,
        )

    # Implementation selection: the pallas MXU-interpolation kernel is the
    # fast path on real TPU hardware; fall back to the pure-XLA tabulated
    # path if it fails to compile/run or misses the 1e-6 contract.
    default_impl = "pallas" if jax.devices()[0].platform != "cpu" else "tabulated"

    def main_measurement():
        """Engine selection + accuracy gates + the timed full-grid sweep
        — the expensive heart of the main metric line, returned as a
        JSON-serializable dict so a tpu_unavailable round can reuse a
        prior round's CPU measurement through the leg cache instead of
        re-paying the full sweep after every relay death."""
        impl = os.environ.get("BDLZ_BENCH_IMPL", default_impl)
        run_chunk = None
        preflight = None
        pallas_reduce = None  # the tier actually benched (for the JSON)
        max_rel = None
        if impl == "pallas":
            # Tier selection through the SHARED resolver
            # (bdlz_tpu.parallel.sweep.resolve_pallas_tier): the reduction
            # kernel degrades to the streaming kernel exactly like the
            # production sweep would, so the bench cannot report a pallas
            # number the sweep engine wouldn't reproduce.
            try:
                from bdlz_tpu.parallel.sweep import resolve_pallas_tier

                fuse = os.environ.get("BDLZ_BENCH_FUSE_EXP", "0") == "1"
                # at the bench's own n_y — lowering failures are
                # shape-dependent (the r2 RecursionError needed n_y=8000)
                tier, preflight = resolve_pallas_tier(
                    static.chi_stats, n_y, fuse_exp=fuse
                )
                if preflight is not None:
                    print(f"[bench] pallas preflight {preflight}",
                          file=sys.stderr)
                if tier is None:
                    raise RuntimeError(f"preflight {preflight}")
                run_chunk = make_run_chunk("pallas", reduce=tier)
                max_rel = max(
                    accuracy_gate(run_chunk),
                    population_gate("pallas", reduce=tier),
                )
                if max_rel > 1e-6:
                    raise RuntimeError(
                        f"pallas(reduce={tier}) rel err {max_rel:.3e} > 1e-6"
                    )
                pallas_reduce = tier
            except Exception as exc:  # noqa: BLE001 — any failure → safe path
                print(f"[bench] pallas path unavailable ({exc}); falling back",
                      file=sys.stderr)
                impl, run_chunk = "tabulated", None
        gate_error = None
        if run_chunk is None:
            from bdlz_tpu.validation import GateFailure

            run_chunk = make_run_chunk(impl)
            try:
                max_rel = max(
                    accuracy_gate(run_chunk, static_run=static_for(impl)),
                    population_gate(impl),
                )
            except GateFailure as exc:
                # non-finite gate output on the LAST-RESORT engine: report
                # the failure in-band (null rel err + gate_error) rather
                # than dying without the driver-parsed final line.  Only the
                # dedicated type — a misconfigured grid should still die
                # loudly, not emit a normal-looking metric line.
                max_rel, gate_error = None, str(exc)
                print(f"[bench] accuracy gate failed: {exc}", file=sys.stderr)

        # --- timed sweep over the full grid ---
        t0 = time.time()
        done = 0
        while done < n_total:
            hi = min(done + chunk, n_total)
            out = run_chunk(done, hi)
            done = hi
        out.block_until_ready()
        seconds = time.time() - t0
        return {
            "impl": impl,
            "preflight": preflight,
            "pallas_reduce": pallas_reduce,
            "max_rel": None if max_rel is None else float(max_rel),
            "gate_error": gate_error,
            "seconds": seconds,
            "per_chip": n_total / seconds / n_dev,
        }

    _main_hit = leg_lookup("main_sweep")
    main_cached = _main_hit is not None
    if main_cached:
        meas = _main_hit["summary"]
    else:
        meas = main_measurement()
        leg_record("main_sweep", [], meas)
    impl = meas["impl"]
    preflight = meas["preflight"]
    pallas_reduce = meas["pallas_reduce"]
    max_rel = meas["max_rel"]
    gate_error = meas["gate_error"]
    seconds = meas["seconds"]
    per_chip = meas["per_chip"]

    main_static = static_for(impl)
    quad_impl_main = "panel_gl" if main_static.quad_panel_gl else "trap"
    n_quad_main = (
        n_quad_gl if main_static.quad_panel_gl else max(n_y, 2000)
    )

    # --- secondary metric: the panel-quadrature A/B (quad_gl) ----------
    # Times the tabulated engine under BOTH y-quadratures on a bounded
    # subset of the bench grid: vs_trapezoid is the measured panel-GL
    # speedup, rel_err_vs_reference the panel path's own gate (engine vs
    # the equal-scheme NumPy reference over the adversarial population),
    # and scheme_vs_trapezoid_rel_err the honest scheme difference on
    # the subset — the "<=1e-9 vs the 8000-node trapezoid" claim,
    # measured every round.
    def quad_gl_metric():
        from bdlz_tpu.validation import relative_errors

        n_sub = int(os.environ.get(
            "BDLZ_BENCH_QUAD_POINTS", min(n_total, 2 * chunk)
        ))
        n_sub = max(min(n_sub, n_total), 1)
        pp_sub = jax.tree.map(lambda a: np.asarray(a)[:n_sub], pp_all)
        run_gl = make_run_chunk("tabulated", pp=pp_sub, static_run=static_gl)
        run_tr = make_run_chunk("tabulated", pp=pp_sub, static_run=static)

        def timed(run):
            vals = np.empty(n_sub)
            out = run(0, min(chunk, n_sub))  # compile warm-up
            out.block_until_ready()
            t1 = time.time()
            done = 0
            while done < n_sub:
                hi = min(done + chunk, n_sub)
                out = run(done, hi)
                vals[done:hi] = np.asarray(out)[: hi - done]
                done = hi
            jax.block_until_ready(out)
            return vals, time.time() - t1

        vals_gl, sec_gl = timed(run_gl)
        vals_tr, sec_tr = timed(run_tr)
        scheme_rel = float(np.max(relative_errors(vals_gl, vals_tr)))
        gl_gate = max(
            accuracy_gate(run_gl, pp=pp_sub, static_run=static_gl),
            population_gate("tabulated", static_run=static_gl),
        )
        per_chip_gl = round(n_sub / sec_gl / n_dev, 2)
        per_chip_tr = round(n_sub / sec_tr / n_dev, 2)
        payload = {
            "metric": "quad_gl_sweep_points_per_sec_per_chip",
            "value": per_chip_gl,
            "unit": "param-points/sec/chip (tabulated engine, snapped-"
                    "panel Gauss-Legendre y-quadrature A/B vs the "
                    "n_y=%d trapezoid)" % n_y,
            "n_points": n_sub,
            # robustness schema: every sweep metric line carries the
            # failure counters (nulls where the leg has no healing path)
            "n_failed": int((~np.isfinite(vals_gl)).sum()),
            "n_quarantined": None,
            "n_retries": None,
            "cache_hits": None,
            "cache_misses": None,
            "quad_impl": "panel_gl",
            "n_quad_nodes": n_quad_gl,
            "vs_trapezoid": round(per_chip_gl / max(per_chip_tr, 1e-9), 1),
            "trapezoid_points_per_sec_per_chip": per_chip_tr,
            "rel_err_vs_reference": float(f"{gl_gate:.3e}"),
            "scheme_vs_trapezoid_rel_err": float(f"{scheme_rel:.3e}"),
            "resolved_on": bool(quad_on),
            "audit": None if quad_audit is None else {
                "ok": quad_audit.ok,
                "reason": quad_audit.reason or None,
                "n_sampled": quad_audit.n_sampled,
                "max_rel_vs_trap": quad_audit.max_rel_vs_trap,
                "max_err_half": quad_audit.max_err_half,
                "max_err_quarter": quad_audit.max_err_quarter,
            },
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "vs_trapezoid", "rel_err_vs_reference",
                "scheme_vs_trapezoid_rel_err", "resolved_on",
            )
        }

    quad_gl_summary = None
    try:
        quad_gl_summary = run_leg("quad_gl", quad_gl_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] quad_gl metric unavailable: {exc}", file=sys.stderr)

    # --- secondary metric: the stiff (ESDIRK) sweep engine ---
    # Sweeps touching sigma_v/washout/depletion auto-route to the vmapped
    # ESDIRK integrator; its throughput is a different regime entirely and
    # gets its own (non-final) metric line plus a field in the main JSON.
    on_cpu = jax.devices()[0].platform == "cpu"

    def esdirk_metric():
        import dataclasses

        from bdlz_tpu.parallel.sweep import make_sweep_step
        from bdlz_tpu.physics.percolation import make_kjma_grid as _mkg
        from bdlz_tpu.utils.profiling import CompactionStats

        # The grid size is PINNED at 1024 on every platform (the stiff
        # drift fix: BENCH_r02 measured 1024 points, r05 only 64 — the
        # two throughputs were not comparable rounds of one trajectory).
        # BDLZ_BENCH_STIFF_POINTS overrides; the legacy
        # BDLZ_BENCH_ODE_POINTS name keeps working.  A relay-dead CPU
        # round now pays the same grid once — and the PR-7 leg cache
        # replays it on later degraded rounds, so the pin does not
        # re-tax every relay death.
        ode_n = int(
            os.environ.get("BDLZ_BENCH_STIFF_POINTS")
            or os.environ.get("BDLZ_BENCH_ODE_POINTS")
            or 1024
        )
        base_ode = dataclasses.replace(
            base, Gamma_wash_over_H=0.01, T_min_over_Tp=0.05
        )
        static_ode = static_choices_from_config(base_ode)
        side_o = max(2, int(round(ode_n ** 0.5)))
        pp_ode = build_grid(base_ode, {
            "m_chi_GeV": np.geomspace(0.3, 3.0, side_o),
            "Gamma_wash_over_H": np.linspace(0.005, 0.1, side_o),
        })
        n_ode = int(np.asarray(pp_ode.m_chi_GeV).shape[0])
        grid_j = _mkg(jnp)
        # pad to a device multiple (side_o**2 need not divide n_dev)
        pad_n = ((n_ode + n_dev - 1) // n_dev) * n_dev
        ppc = _pad_chunk(pp_ode, 0, n_ode, pad_n)
        ppc_dev = jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), sharding), ppc
        )

        def time_engine(impl, **kw):
            step = make_sweep_step(static_ode, mesh=mesh, impl=impl, **kw)
            out = step(ppc_dev, grid_j).DM_over_B
            jax.block_until_ready(out)  # compile warm-up
            t1 = time.time()
            out = step(ppc_dev, grid_j).DM_over_B
            jax.block_until_ready(out)
            return np.asarray(out)[:n_ode], time.time() - t1

        # A/B: the lane-repacking batch engine (the sweep default) vs the
        # legacy lockstep strategy — the speedup is the round's headline
        # stiff-engine evidence, so it is measured, not asserted.
        out_lock, lock_seconds = time_engine("esdirk_lockstep")
        stats_box = []
        out_ode, esdirk_seconds = time_engine(
            "esdirk", esdirk_stats_sink=stats_box.append
        )
        per_chip_ode = round(n_ode / esdirk_seconds / n_dev, 2)
        per_chip_lock = round(n_ode / lock_seconds / n_dev, 2)
        both = np.isfinite(out_ode) & np.isfinite(out_lock) & (out_lock != 0)
        rel_vs_lock = (
            float(np.max(np.abs(out_ode[both] / out_lock[both] - 1.0)))
            if both.any() else None
        )
        stats = stats_box[-1].summary() if stats_box else CompactionStats().summary()

        # "equal rel_err_vs_reference": both engines against the scalar
        # pulse-capped exact-kernel Radau truth (the cross-check the test
        # battery pins at 1e-6) on a few grid corners — ~1.2 s/point, so
        # a spot sample, not the grid
        from bdlz_tpu.models.yields_pipeline import present_day
        from bdlz_tpu.solvers.boltzmann import solve_scipy_radau

        # None until a spot is actually measured — an all-skipped sample
        # (Radau non-convergence, engine NaN at the corners) must report
        # null, never a fabricated-perfect 0.0
        rel_ref = {"esdirk": None, "lockstep": None}
        for i in (0, n_ode // 2, n_ode - 1):
            pp_i = type(pp_ode)(*(float(np.asarray(f)[i]) for f in pp_ode))
            T_lo_i = pp_i.T_min_over_Tp * pp_i.T_p_GeV
            T_hi_i = pp_i.T_max_over_Tp * pp_i.T_p_GeV
            ref = solve_scipy_radau(
                pp_i, static_ode.chi_stats,
                static_ode.deplete_DM_from_source, _mkg(np),
                (pp_i.Y_chi_init, 0.0), T_lo_i, T_hi_i,
                rtol=1e-10, atol=1e-20, reference_step_cap=False,
                pulse_step_cap=True, table_n=None,
            )
            if not ref.success:
                continue
            ref_ratio = float(present_day(
                ref.Y_B, ref.Y_chi, pp_i.m_chi_GeV, pp_i.m_B_kg, np
            ).DM_over_B)
            if ref_ratio == 0.0 or not np.isfinite(ref_ratio):
                continue
            for name, arr in (("esdirk", out_ode), ("lockstep", out_lock)):
                val = float(arr[i])
                if not np.isfinite(val):
                    continue  # the n_failed field already reports NaNs
                err = abs(val / ref_ratio - 1.0)
                rel_ref[name] = (
                    err if rel_ref[name] is None else max(rel_ref[name], err)
                )
        emit(
            {
                "metric": "esdirk_sweep_points_per_sec_per_chip",
                "value": per_chip_ode,
                "unit": "stiff ODE param-points/sec/chip (Gamma_wash grid)",
                # the engine the headline number measures (the lockstep
                # A/B rides the *_lockstep fields) + the pinned grid
                # size, so rounds are comparable by construction
                "engine": "esdirk",
                "lockstep_engine": "esdirk_lockstep",
                "n_points": n_ode,
                "n_failed": int((~np.isfinite(out_ode)).sum()),
                # this leg times raw engine steps (no chunk-healing loop)
                "n_quarantined": None,
                "n_retries": None,
                "cache_hits": None,
                "cache_misses": None,
                "seconds": round(esdirk_seconds, 3),
                # the lockstep A/B: same grid, same tolerances, legacy
                # engine — vs_lockstep is the repacking+accelerations
                # speedup at the rel_err recorded next to it
                "vs_lockstep": round(per_chip_ode / max(per_chip_lock, 1e-9), 1),
                "lockstep_points_per_sec_per_chip": per_chip_lock,
                "lockstep_seconds": round(lock_seconds, 3),
                "rel_err_vs_lockstep": (
                    None if rel_vs_lock is None
                    else float(f"{rel_vs_lock:.3e}")
                ),
                # spot sample vs the pulse-capped exact-kernel Radau truth
                # (3 grid corners) for BOTH engines — "3x at equal
                # accuracy" needs the accuracy measured on the same line;
                # null = no spot could be measured, NOT perfect accuracy
                "rel_err_vs_reference": (
                    None if rel_ref["esdirk"] is None
                    else float(f"{rel_ref['esdirk']:.3e}")
                ),
                "lockstep_rel_err_vs_reference": (
                    None if rel_ref["lockstep"] is None
                    else float(f"{rel_ref['lockstep']:.3e}")
                ),
                "compaction": stats,
                # no y-quadrature exists on the stiff path; nulls keep
                # the "every sweep metric line names its quadrature"
                # schema uniform
                "quad_impl": None,
                "n_quad_nodes": None,
                "platform": jax.devices()[0].platform,
                "tpu_unavailable": tpu_unavailable,
            }
        )
        return per_chip_ode

    esdirk_per_chip = None
    try:
        esdirk_per_chip = run_leg("esdirk", esdirk_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] esdirk metric unavailable: {exc}", file=sys.stderr)

    # --- secondary metric: chaos (self-healing sweep under faults) ----
    # Runs the production run_sweep twice on a small grid: clean, then
    # under a canned deterministic fault plan (transient step error on
    # chunk 0, one poison point the bisect must isolate, one
    # NaN-poisoned point).  The line records the healed throughput vs
    # clean, the quarantine/retry counters, and whether every
    # unaffected point came back BIT-identical to the clean run — the
    # robustness trajectory, measured every round like the perf one.
    def chaos_metric():
        import dataclasses

        from bdlz_tpu.faults import FaultPlan
        from bdlz_tpu.parallel.sweep import run_sweep
        from bdlz_tpu.utils.retry import RetryPolicy

        n_chaos = int(os.environ.get("BDLZ_BENCH_CHAOS_POINTS", 64))
        side_c = max(2, int(round(n_chaos ** 0.5)))
        axes_c = {
            "m_chi_GeV": np.geomspace(0.3, 3.0, side_c),
            "T_p_GeV": np.geomspace(60.0, 200.0, side_c),
        }
        n_c = side_c * side_c
        chunk_c = max(n_dev, ((side_c + n_dev - 1) // n_dev) * n_dev)
        poison = n_c // 3
        nan_pt = (2 * n_c) // 3
        plan = FaultPlan.from_obj({"faults": [
            {"site": "step", "kind": "transient", "key": 0, "times": 1},
            {"site": "step", "kind": "poison", "point": poison},
            {"site": "step", "kind": "nan", "point": nan_pt},
        ]})
        retry = RetryPolicy(max_attempts=2, backoff_s=0.0,
                            sleep=lambda s: None)
        static_c = static_for("tabulated")
        # the clean baseline must be INSULATED from any ambient fault
        # plan (an exported BDLZ_FAULT_PLAN would otherwise fault both
        # legs and void the A/B); the chaos leg's explicit plan already
        # overrides the env
        base_clean = dataclasses.replace(base, fault_injection=False)
        t1 = time.time()
        res_clean = run_sweep(
            base_clean, axes_c, static_c, mesh=mesh, chunk_size=chunk_c,
            n_y=n_y,
        )
        clean_seconds = time.time() - t1
        t2 = time.time()
        res_chaos = run_sweep(
            base, axes_c, static_c, mesh=mesh, chunk_size=chunk_c, n_y=n_y,
            fault_plan=plan, retry=retry,
        )
        chaos_seconds = time.time() - t2
        per_chip_chaos = round(n_c / chaos_seconds / n_dev, 2)
        per_chip_clean = round(n_c / clean_seconds / n_dev, 2)
        affected = np.asarray(res_chaos.failed_mask)
        unaffected = ~affected & np.isfinite(res_clean.outputs["DM_over_B"])
        bitwise = bool(np.array_equal(
            res_chaos.outputs["DM_over_B"][unaffected],
            res_clean.outputs["DM_over_B"][unaffected],
        ))
        payload = {
            "metric": "chaos_sweep_points_per_sec_per_chip",
            "value": per_chip_chaos,
            "unit": "param-points/sec/chip (run_sweep under a canned "
                    "fault plan: transient chunk error + poison point + "
                    "NaN point, retry/bisect/quarantine healing on)",
            "n_points": n_c,
            "n_failed": int(res_chaos.n_failed),
            "n_quarantined": int(res_chaos.n_quarantined),
            "n_retries": int(res_chaos.n_retries),
            "cache_hits": res_chaos.cache_hits,
            "cache_misses": res_chaos.cache_misses,
            "clean_points_per_sec_per_chip": per_chip_clean,
            "vs_clean": round(per_chip_chaos / max(per_chip_clean, 1e-9), 3),
            "bitwise_equal_unaffected": bitwise,
            "fault_plan": plan.describe(),
            "quad_impl": "panel_gl" if static_c.quad_panel_gl else "trap",
            "n_quad_nodes": (
                n_quad_gl if static_c.quad_panel_gl else max(n_y, 2000)
            ),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "vs_clean", "n_failed", "n_quarantined",
                "n_retries", "bitwise_equal_unaffected",
            )
        }

    chaos_summary = None
    try:
        chaos_summary = run_leg("chaos", chaos_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] chaos metric unavailable: {exc}", file=sys.stderr)

    # --- secondary metric: sweep_churn (elastic fleet under churn) ----
    # The elastic work-stealing scheduler (parallel/scheduler.py) on the
    # chaos grid, under OPERATIONAL churn — a worker crash mid-chunk, a
    # flaky lease claim, a torn store read, plus a scripted kill/spawn —
    # against a serial single-host baseline of the same grid.  The line
    # records healed elastic throughput, the churn counters, and the
    # contract the whole subsystem exists for: every output field comes
    # back BITWISE-equal to the serial engine despite the unreliable
    # fleet.
    def sweep_churn_metric():
        import dataclasses
        import shutil
        import tempfile

        from bdlz_tpu.faults import FaultPlan
        from bdlz_tpu.parallel.scheduler import run_sweep_elastic
        from bdlz_tpu.parallel.sweep import run_sweep
        from bdlz_tpu.utils.retry import RetryPolicy

        n_churn = int(os.environ.get(
            "BDLZ_BENCH_CHURN_POINTS",
            os.environ.get("BDLZ_BENCH_CHAOS_POINTS", 64),
        ))
        side_e = max(2, int(round(n_churn ** 0.5)))
        axes_e = {
            "m_chi_GeV": np.geomspace(0.3, 3.0, side_e),
            "T_p_GeV": np.geomspace(60.0, 200.0, side_e),
        }
        n_e = side_e * side_e
        chunk_e = max(2, (side_e // 2) * 2)
        churn = FaultPlan.from_obj({"faults": [
            {"site": "worker_crash", "kind": "transient", "chunk": 1,
             "times": 1},
            {"site": "lease", "kind": "transient", "chunk": 0, "times": 1},
            {"site": "store_read", "kind": "torn", "call": 0},
        ]})
        retry = RetryPolicy(max_attempts=2, backoff_s=0.0,
                            sleep=lambda s: None)
        static_e = static_for("tabulated")
        # churn is operational-only: the result-identity fault plane is
        # OFF on both legs, so serial and elastic share chunk identity
        base_clean = dataclasses.replace(base, fault_injection=False)
        t1 = time.time()
        res_serial = run_sweep(
            base_clean, axes_e, static_e, mesh=None, chunk_size=chunk_e,
            n_y=n_y,
        )
        serial_seconds = time.time() - t1
        root = tempfile.mkdtemp(prefix="bdlz_bench_sweep_churn_")
        try:
            t2 = time.time()
            res_churn = run_sweep_elastic(
                base_clean, axes_e, static_e, store=root,
                chunk_size=chunk_e, n_y=n_y, retry=retry, n_workers=2,
                lease_ttl_s=5.0, churn_plan=churn,
                churn_schedule=[(1, "kill"), (2, "spawn")],
            )
            churn_seconds = time.time() - t2
        finally:
            shutil.rmtree(root, ignore_errors=True)
        bitwise = bool(
            all(
                np.array_equal(res_churn.outputs[f], res_serial.outputs[f])
                for f in res_serial.outputs
            )
            and np.array_equal(res_churn.failed_mask, res_serial.failed_mask)
            and np.array_equal(
                res_churn.quarantined_mask, res_serial.quarantined_mask
            )
        )
        churn_pps = round(n_e / churn_seconds, 2)
        serial_pps = round(n_e / serial_seconds, 2)
        payload = {
            "metric": "sweep_churn_points_per_sec",
            "value": churn_pps,
            "unit": "param-points/sec (run_sweep_elastic, 2-worker "
                    "in-process fleet under churn: worker crash + flaky "
                    "lease + torn store read + scripted kill/spawn)",
            "n_points": n_e,
            "n_chunks": res_churn.chunks,
            "n_failed": int(res_churn.n_failed),
            "n_quarantined": int(res_churn.n_quarantined),
            "n_retries": int(res_churn.n_retries),
            "cache_hits": res_churn.cache_hits,
            "cache_misses": res_churn.cache_misses,
            "serial_points_per_sec": serial_pps,
            "vs_serial": round(churn_pps / max(serial_pps, 1e-9), 3),
            "bitwise_equal": bitwise,
            "churn_plan": churn.describe(),
            "lease_ttl_s": 5.0,
            "n_workers": 2,
            "quad_impl": "panel_gl" if static_e.quad_panel_gl else "trap",
            "n_quad_nodes": (
                n_quad_gl if static_e.quad_panel_gl else max(n_y, 2000)
            ),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "vs_serial", "n_failed", "n_quarantined",
                "n_retries", "bitwise_equal",
            )
        }

    sweep_churn_summary = None
    try:
        sweep_churn_summary = run_leg("sweep_churn", sweep_churn_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] sweep_churn metric unavailable: {exc}", file=sys.stderr)

    # --- secondary metric: the provenance sweep-chunk cache ------------
    # Builds a small emulator box COLD into a fresh content-addressed
    # store, then rebuilds it WARM against the same store
    # (docs/provenance.md): the line records the warm/cold speedup, the
    # warm hit rate, and — the contract that makes caching admissible at
    # all — that the warm surface is BIT-identical to the cold one.
    # Quadrature is pinned to the trapezoid so both legs skip the
    # (equal-cost) audit and the cold compute is an honest heavyweight.
    def sweep_cache_metric():
        import shutil
        import tempfile

        from bdlz_tpu.emulator import AxisSpec, build_emulator
        from bdlz_tpu.provenance import Store

        nodes0 = int(os.environ.get("BDLZ_BENCH_CACHE_NODES", 4))
        cache_ny = int(os.environ.get("BDLZ_BENCH_CACHE_NY", n_y))
        probes = int(os.environ.get("BDLZ_BENCH_CACHE_PROBES", 16))
        rounds = int(os.environ.get("BDLZ_BENCH_CACHE_ROUNDS", 2))
        static_cc = static._replace(quad_panel_gl=False)
        spec = {
            "m_chi_GeV": AxisSpec(0.3, 3.0, nodes0, "log"),
            "T_p_GeV": AxisSpec(60.0, 200.0, nodes0, "log"),
        }
        root = tempfile.mkdtemp(prefix="bdlz_bench_sweep_cache_")
        try:
            kw = dict(
                rtol=1e-3, n_probe=probes, max_rounds=rounds,
                n_y=cache_ny, impl="tabulated", mesh=mesh,
                chunk_size=max(64, n_dev), seed=5,
            )
            store_cold = Store(root)
            t1 = time.time()
            art_cold, rep_cold = build_emulator(
                base, spec, static_cc, cache=store_cold, **kw
            )
            cold_s = time.time() - t1
            store_warm = Store(root)
            t2 = time.time()
            art_warm, _rep_warm = build_emulator(
                base, spec, static_cc, cache=store_warm, **kw
            )
            warm_s = time.time() - t2
        finally:
            shutil.rmtree(root, ignore_errors=True)
        bitwise = all(
            np.array_equal(art_cold.values[f], art_warm.values[f])
            for f in art_cold.values
        )
        probed = store_warm.stats.hits + store_warm.stats.misses
        speedup = cold_s / max(warm_s, 1e-9)
        payload = {
            "metric": "sweep_cache_warm_vs_cold",
            "value": round(speedup, 1),
            "unit": "x speedup (warm rebuild of the same emulator box "
                    "through the content-addressed sweep chunk cache vs "
                    "cold build; trapezoid n_y=%d)" % cache_ny,
            "cold_seconds": round(cold_s, 3),
            "warm_seconds": round(warm_s, 3),
            "cache_hits": int(store_warm.stats.hits),
            "cache_misses": int(store_warm.stats.misses),
            "hit_rate": round(store_warm.stats.hits / max(probed, 1), 4),
            "bitwise_equal": bitwise,
            "n_grid_points": art_cold.n_points,
            "n_exact_evals": rep_cold.n_exact_evals,
            # schema: the build raises on any failed/quarantined grid
            # point, so a line that printed at all had zero of each
            "n_failed": 0,
            "n_quarantined": None,
            "n_retries": None,
            "quad_impl": "trap",
            "n_quad_nodes": max(cache_ny, 2000),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "cold_seconds", "warm_seconds", "cache_hits",
                "cache_misses", "hit_rate", "bitwise_equal",
            )
        }

    sweep_cache_summary = None
    try:
        sweep_cache_summary = run_leg("sweep_cache", sweep_cache_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] sweep_cache metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: the yield-surface emulator + query service ---
    # Builds a small adaptive emulator (bdlz_tpu/emulator) over the bench
    # grid's (m_chi, T_p) box by driving the exact sweep engine, then
    # times batched log-space interpolation queries against the exact
    # per-point path it replaces.  The serving claim ("answers from the
    # surface in microseconds") is measured every round, with the
    # held-out accuracy number on the same line.
    def emulator_metric():
        from bdlz_tpu.emulator import (
            AxisSpec,
            build_emulator,
            make_exact_evaluator,
            make_query_fn,
        )

        emu_rtol = float(os.environ.get("BDLZ_BENCH_EMU_RTOL", 1e-4))
        emu_rounds = int(os.environ.get("BDLZ_BENCH_EMU_ROUNDS", 25))
        emu_probes = int(os.environ.get("BDLZ_BENCH_EMU_PROBES", 48))
        n_queries = int(os.environ.get("BDLZ_BENCH_EMU_QUERIES",
                                       8192 if on_cpu else 65536))
        n_exact = int(os.environ.get("BDLZ_BENCH_EMU_EXACT_POINTS",
                                     min(256 if on_cpu else 2048, n_queries)))
        # The box mixes power-law directions the log axes absorb for free
        # (m_chi, T_p, beta — they land on 3-5 nodes) with the source
        # width sigma_y, whose genuine curvature is what the ADAPTIVE
        # refinement has to chase (measured: ~200 nodes at rtol 1e-4) —
        # so the recorded build cost exercises both regimes.
        base_emu = base
        static_emu = static
        spec = {
            "m_chi_GeV": AxisSpec(0.1, 10.0, 3, "log"),
            "T_p_GeV": AxisSpec(30.0, 300.0, 5, "log"),
            "source_shape_sigma_y": AxisSpec(3.0, 18.0, 5, "lin"),
            "beta_over_H": AxisSpec(50.0, 500.0, 5, "log"),
        }
        t_build = time.time()
        artifact, report = build_emulator(
            base_emu, spec, static_emu, rtol=emu_rtol, n_probe=emu_probes,
            max_rounds=emu_rounds, n_y=n_y, impl="tabulated",
            chunk_size=chunk,
        )
        build_seconds = time.time() - t_build

        rng = np.random.default_rng(7)
        thetas = np.stack([
            10 ** rng.uniform(-1.0, 1.0, n_queries),
            10 ** rng.uniform(np.log10(30.0), np.log10(300.0), n_queries),
            rng.uniform(3.0, 18.0, n_queries),
            10 ** rng.uniform(np.log10(50.0), np.log10(500.0), n_queries),
        ], axis=1)
        query = make_query_fn(artifact)
        out = query(thetas)           # compile warm-up (one batch shape)
        out.block_until_ready()
        reps = 5
        t1 = time.time()
        for _ in range(reps):
            out = query(thetas)
        out.block_until_ready()
        query_seconds = (time.time() - t1) / reps
        query_pps = n_queries / max(query_seconds, 1e-9)

        # the exact per-point path the emulator replaces, same engine/n_y
        exact_eval = make_exact_evaluator(
            base_emu, static_emu, n_y=n_y, impl="tabulated",
            chunk_size=min(chunk, n_exact),
        )
        axes_exact = {
            "m_chi_GeV": thetas[:n_exact, 0],
            "T_p_GeV": thetas[:n_exact, 1],
            "source_shape_sigma_y": thetas[:n_exact, 2],
            "beta_over_H": thetas[:n_exact, 3],
        }
        exact_eval(axes_exact)        # compile warm-up
        t2 = time.time()
        exact_vals = exact_eval(axes_exact)["DM_over_B"]
        exact_seconds = time.time() - t2
        exact_pps = n_exact / max(exact_seconds, 1e-9)

        # spot-check the served values against the exact outputs just
        # computed (independent of the build's own held-out gate)
        from bdlz_tpu.validation import relative_errors

        spot_rel = float(np.max(relative_errors(
            np.asarray(out)[:n_exact], np.asarray(exact_vals)
        )))

        payload = {
            "metric": "emulator_query_points_per_sec",
            "value": round(query_pps, 1),
            "unit": "emulator queries/sec (batched log-space interpolation, "
                    "full query batch)",
            "n_queries": n_queries,
            "query_seconds": round(query_seconds, 6),
            "build_seconds": round(build_seconds, 3),
            "refinement_rounds": len(report.rounds),
            "n_exact_evals": report.n_exact_evals,
            "grid_points": artifact.n_points,
            "rtol_target": emu_rtol,
            "max_rel_err": float(f"{report.max_rel_err:.3e}"),
            "spot_rel_err": float(f"{spot_rel:.3e}"),
            "converged": bool(report.converged),
            "exact_points_per_sec": round(exact_pps, 2),
            "vs_exact": round(query_pps / max(exact_pps, 1e-9), 1),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        summary = {
            k: payload[k] for k in (
                "build_seconds", "refinement_rounds", "max_rel_err",
                "converged", "vs_exact",
            )
        } | {"query_points_per_sec": payload["value"]}
        # the artifact rides along for the serve_bench leg (one build
        # per round; the fleet must serve the surface this round built)
        return summary, artifact

    emulator_summary = None
    emu_artifact = None
    _emu_box: list = []

    def emulator_leg():
        # the artifact itself is not JSON (not cacheable); it rides a
        # side box so a cache HIT yields summary-only — the serve leg
        # then answers from its own cached entry or skips loudly
        s, art = emulator_metric()
        _emu_box.append(art)
        return s

    try:
        emulator_summary = run_leg("emulator", emulator_leg)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] emulator metric unavailable: {exc}", file=sys.stderr)
    emu_artifact = _emu_box[0] if _emu_box else None

    # --- secondary metric: the sharded serving fleet (serve_bench) ----
    # The serving counterpart of sweep_points_per_sec_per_chip
    # (docs/serving.md): replicate the round's emulator artifact onto
    # every local device (bdlz_tpu/serve/fleet.py), stream the same
    # request stream through 1 replica and N replicas (bit-identity
    # checked), pump a closed-loop request plane for latency
    # percentiles, and run a canned fake-clock overload trace against
    # the bounded queue + deadline shedding so the shed rate is a
    # DETERMINISTIC function of the trace, not of host timing.
    def serve_bench_metric(artifact):
        from collections import deque

        from bdlz_tpu.serve.batcher import QueueFull
        from bdlz_tpu.serve.fleet import FleetService, ReplicaSet

        n_q = int(os.environ.get("BDLZ_BENCH_SERVE_QUERIES",
                                 16384 if on_cpu else 262144))
        srv_batch = int(os.environ.get("BDLZ_BENCH_SERVE_BATCH", 4096))
        srv_batch = max(1, min(srv_batch, n_q))
        n_rep = int(os.environ.get("BDLZ_BENCH_SERVE_REPLICAS",
                                   min(4, n_dev)))
        rng = np.random.default_rng(11)
        lo = np.array([nodes[0] for nodes in artifact.axis_nodes])
        hi = np.array([nodes[-1] for nodes in artifact.axis_nodes])
        thetas = rng.uniform(lo, hi, size=(n_q, len(lo)))

        def throughput(n_replicas):
            # raw micro-batch routing (the aggregate-QPS product): keep
            # two batches in flight per replica so devices overlap
            rs = ReplicaSet(
                artifact, n_replicas=n_replicas,
                max_batch_size=srv_batch, routing="least_loaded",
            )
            vals = np.empty(n_q)
            handles = deque()
            t0 = time.time()
            for lo_i in range(0, n_q, srv_batch):
                hi_i = min(lo_i + srv_batch, n_q)
                handles.append(
                    (lo_i, hi_i, rs.dispatch(thetas[lo_i:hi_i]))
                )
                if len(handles) > 2 * n_replicas:
                    a, b, h = handles.popleft()
                    vals[a:b] = h.gather()[0]
            while handles:
                a, b, h = handles.popleft()
                vals[a:b] = h.gather()[0]
            seconds = time.time() - t0
            return vals, n_q / max(seconds, 1e-9), rs

        vals1, qps1, _ = throughput(1)
        vals_n, qps_n, rs_n = throughput(n_rep)
        # the acceptance contract: same stream, BIT-identical responses
        # at any replica count (same kernel, same table bytes, per
        # device) — scaling must never buy a different answer
        bit_identical = bool(np.array_equal(vals1, vals_n))
        replica_scaling = qps_n / max(qps1, 1e-9)
        qps_per_chip = qps_n / rs_n.n_devices

        # request-plane latency percentiles: closed-loop pump through
        # the per-request future front (real clock — these are the p50/
        # p99 a caller would see)
        n_lat = int(os.environ.get("BDLZ_BENCH_SERVE_LAT_QUERIES",
                                   min(4096, n_q)))
        lat_batch = min(256, srv_batch)
        svc = FleetService(
            artifact, base, max_batch_size=lat_batch, n_replicas=n_rep,
            max_wait_s=5e-4,
        )
        futs = []
        for i in range(n_lat):
            futs.append(svc.submit(thetas[i % n_q]))
            svc.run_once()
            svc.poll(block=False)
        svc.drain()
        for f in futs:
            f.result(timeout=0)  # surface any per-request failure loudly
        lat_summary = svc.stats.summary()

        # canned overload trace (fake clock): 8 bursts, each offering a
        # full queue bound; one dispatch drains lat_batch per burst, so
        # admission must reject the excess and the deadline must kill
        # the aged tail — the shed rate is a pure function of the trace
        class _Tick:
            t = 0.0

            def __call__(self):
                return self.t

        tick = _Tick()
        q_bound = 2 * lat_batch
        ov = FleetService(
            artifact, base, max_batch_size=lat_batch, n_replicas=n_rep,
            queue_bound=q_bound, max_wait_s=1e-3, deadline_s=0.05,
            clock=tick,
        )
        offered = 0
        ov_futs = []
        for _burst in range(8):
            for _k in range(q_bound):
                offered += 1
                try:
                    ov_futs.append(ov.submit(thetas[offered % n_q]))
                except QueueFull:
                    pass
            ov.run_once()
            ov.poll(block=False)
            tick.t += 0.02
        ov.drain()
        ov_summary = ov.stats.summary()

        try:
            host_cores = len(os.sched_getaffinity(0))
        except AttributeError:  # non-linux fallback
            host_cores = os.cpu_count()

        payload = {
            "metric": "serve_bench_queries_per_sec_per_chip",
            "value": round(qps_per_chip, 1),
            "unit": "emulator serve QPS/chip (per-device replica fleet, "
                    "least-loaded micro-batch routing, batch %d)"
                    % srv_batch,
            "n_queries": n_q,
            "n_replicas": n_rep,
            "n_replica_devices": rs_n.n_devices,
            # replica scaling is bounded by physical parallelism: on a
            # CPU fallback host the replicas share host_cores, so ~1.0
            # there is expected — the chip-count scaling claim is a
            # hardware number, flagged like every other leg
            "host_cores": host_cores,
            "qps": round(qps_n, 1),
            "single_replica_qps": round(qps1, 1),
            "replica_scaling": round(replica_scaling, 2),
            "bit_identical_across_replicas": bit_identical,
            "warmup_seconds": round(rs_n.warmup_seconds, 4),
            "routing": "least_loaded",
            "artifact_hash": artifact.content_hash,
            "latency_queries": n_lat,
            "p50_latency_s": lat_summary["p50_latency_s"],
            "p99_latency_s": lat_summary["p99_latency_s"],
            "mean_occupancy": lat_summary["mean_occupancy"],
            "shed_rate": ov_summary["shed_rate"],
            "admission_rejects": ov_summary["admission_rejects"],
            "deadline_kills": ov_summary["deadline_kills"],
            "overload_offered": offered,
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "qps", "replica_scaling", "p50_latency_s",
                "p99_latency_s", "shed_rate",
                "bit_identical_across_replicas",
            )
        }

    serve_summary = None
    try:
        _serve_hit = leg_lookup("serve_bench")
        if _serve_hit is not None:
            serve_summary = _serve_hit.get("summary")
        elif emu_artifact is None:
            # no fresh artifact (emulator leg failed, or it was itself a
            # cache hit without a matching serve entry — possible only
            # if the prior round's serve leg failed): nothing to serve
            print("[bench] serve_bench skipped: no emulator artifact this "
                  "round", file=sys.stderr)
        else:
            serve_summary = run_leg(
                "serve_bench", lambda: serve_bench_metric(emu_artifact)
            )
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] serve_bench metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: chaos_serve (self-healing fleet) ------------
    # The serving counterpart of the chaos sweep line: the SAME request
    # stream is pushed through a 2-replica fleet clean and under a
    # canned single-replica fault trace (replica 1: transient dispatch
    # errors, then one NaN batch — site replica_dispatch), entirely on
    # a FAKE clock so the whole breaker choreography — trip, cooldown,
    # failed half-open probes, heal, re-close — is a deterministic
    # function of the trace.  The line records availability (answered
    # fraction), p99 under the failure, the breaker recovery time in
    # fake-clock seconds, and whether every answer came back
    # BIT-identical to the clean run (the healed re-answer runs the
    # same fused kernel on the same table bytes).
    def chaos_serve_metric(artifact):
        import dataclasses

        from bdlz_tpu.serve.fleet import FleetService

        n_req = int(os.environ.get("BDLZ_BENCH_CHAOS_SERVE_QUERIES", 768))
        cs_batch = int(os.environ.get("BDLZ_BENCH_CHAOS_SERVE_BATCH", 32))
        cs_batch = max(1, min(cs_batch, n_req))
        n_rep = 2  # canned SINGLE-replica failure needs a >=2 fleet
        rng = np.random.default_rng(17)
        lo = np.array([nodes[0] for nodes in artifact.axis_nodes])
        hi = np.array([nodes[-1] for nodes in artifact.axis_nodes])
        thetas = rng.uniform(lo, hi, size=(n_req, len(lo)))
        plan_obj = {"faults": [
            {"site": "replica_dispatch", "kind": "transient", "key": 1,
             "times": 2},
            {"site": "replica_dispatch", "kind": "nan", "key": 1,
             "times": 1},
        ]}

        class _Tick:
            t = 0.0

            def __call__(self):
                return self.t

        def run(plan_json):
            tick = _Tick()
            cfg = dataclasses.replace(
                base,
                fault_plan=plan_json,
                fault_injection=None if plan_json else False,
                # one bad batch trips a breaker; the short fake-clock
                # cooldown schedules the half-open probes INSIDE the
                # trace (0.01 s per batch tick)
                breaker_window=1, breaker_cooldown_s=0.05,
                # gate off: the A/B compares pure replica-kernel
                # answers (the exact path compiles per service
                # instance; its first-jit-run wobble would void the
                # bitwise pin)
                error_gate_tol=False,
            )
            svc = FleetService(
                artifact, cfg, max_batch_size=cs_batch, n_replicas=n_rep,
                routing="round_robin", max_wait_s=1e-3, clock=tick,
            )
            futs = []
            for i in range(n_req):
                futs.append(svc.submit(thetas[i]))
                if (i + 1) % cs_batch == 0:
                    tick.t += 0.01
                    svc.run_once()
                    svc.poll(block=True)
            svc.drain()
            vals = np.full(n_req, np.nan)
            n_ok = 0
            for i, f in enumerate(futs):
                try:
                    vals[i] = f.result(timeout=0).value
                    n_ok += 1
                except Exception:  # noqa: BLE001 — availability counts these
                    pass
            return vals, n_ok, svc

        t_cs = time.time()
        clean_vals, _clean_ok, _svc_clean = run(None)
        chaos_vals, chaos_ok, svc = run(json.dumps(plan_obj))
        cs_seconds = time.time() - t_cs
        stats = svc.stats.summary()
        health = stats.get("health") or {}
        availability = chaos_ok / n_req
        bitwise = bool(np.array_equal(clean_vals, chaos_vals))
        reclosed = bool(health.get("states")) and all(
            s == "closed" for s in health.get("states", [])
        )
        try:
            host_cores = len(os.sched_getaffinity(0))
        except AttributeError:  # non-linux fallback
            host_cores = os.cpu_count()
        payload = {
            "metric": "chaos_serve_availability",
            "value": round(availability, 4),
            "unit": "answered fraction under a canned single-replica "
                    "replica_dispatch fault trace (2-replica fleet, "
                    "breaker trip/probe/heal cycle on a fake clock, "
                    "batch %d)" % cs_batch,
            "n_requests": n_req,
            "n_replicas": n_rep,
            "host_cores": host_cores,
            # p99 under the single-replica failure (fake-clock seconds
            # — deterministic, comparable round over round)
            "p99_latency_s": stats["p99_latency_s"],
            "p50_latency_s": stats["p50_latency_s"],
            # breaker choreography evidence: trip count, heal count,
            # the open→re-close recovery span, final states
            "breaker_opens": health.get("opens"),
            "breaker_reclosed": reclosed,
            "recovery_s": health.get("last_recovery_s"),
            "healed_batches": health.get("healed_batches"),
            "degraded_batches": health.get("degraded_batches"),
            "bitwise_equal_unaffected": bitwise,
            "wall_seconds": round(cs_seconds, 4),
            "fault_plan": plan_obj["faults"],
            "artifact_hash": artifact.content_hash,
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "p99_latency_s", "recovery_s", "breaker_opens",
                "breaker_reclosed", "healed_batches",
                "bitwise_equal_unaffected",
            )
        }

    chaos_serve_summary = None
    try:
        _cs_hit = leg_lookup("chaos_serve")
        if _cs_hit is not None:
            chaos_serve_summary = _cs_hit.get("summary")
        elif emu_artifact is None:
            print("[bench] chaos_serve skipped: no emulator artifact this "
                  "round", file=sys.stderr)
        else:
            chaos_serve_summary = run_leg(
                "chaos_serve", lambda: chaos_serve_metric(emu_artifact)
            )
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] chaos_serve metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: seam-split emulator domains + error gate ----
    # The PR-3 emulator's documented blind spot: a box crossing the
    # T = m/3 flux seam refines first-order and was "split at the band
    # or serve exact".  This leg measures the split path doing exactly
    # that: an A/B seam-box build (split-domain vs single-domain at
    # equal tolerance — exact-point budget and held-out error on the
    # line) and a deterministic seam-crossing serve trace through the
    # predicted-error-gated YieldService (fallback rate + effective QPS,
    # gated vs ungated, against both artifacts), with the gated answers
    # spot-checked against the exact engine on the same line.
    def seam_split_metric():
        import dataclasses

        from bdlz_tpu.config import static_choices_from_config
        from bdlz_tpu.emulator import (
            AxisSpec,
            build_emulator,
            make_exact_evaluator,
        )
        from bdlz_tpu.serve.service import YieldService
        from bdlz_tpu.validation import relative_errors

        seam_ny = int(os.environ.get("BDLZ_BENCH_SEAM_NY", 200))
        seam_rtol = float(os.environ.get("BDLZ_BENCH_SEAM_RTOL", 1e-4))
        seam_rounds = int(os.environ.get("BDLZ_BENCH_SEAM_ROUNDS", 8))
        n_trace = int(os.environ.get("BDLZ_BENCH_SEAM_QUERIES", 512))
        n_ref = min(int(os.environ.get("BDLZ_BENCH_SEAM_EXACT", 128)),
                    n_trace)
        # sigma_y = 1.5 keeps the seam band narrow enough that the split
        # sides converge at 1e-4 within the round budget while the
        # single-domain build demonstrably cannot (the measured
        # perf_notes pathology, scaled to a bench-sized box)
        base_seam = dataclasses.replace(base, source_shape_sigma_y=1.5)
        spec = {
            "m_chi_GeV": AxisSpec(20.0, 600.0, 3, "log"),
            "T_p_GeV": AxisSpec(95.0, 105.0, 2, "log"),
        }
        # no mesh: this is an accuracy/structure A/B, not a throughput
        # leg, and its small probe chunks (6 rows) are not shardable
        # across a multi-device mesh — the single-device engine is the
        # same arithmetic
        kw = dict(
            rtol=seam_rtol, n_probe=6, n_holdout=48,
            max_rounds=seam_rounds, max_nodes_per_axis=128, n_y=seam_ny,
            impl="tabulated", chunk_size=max(64, n_dev), seed=5,
        )
        t1 = time.time()
        split_art, split_rep = build_emulator(base_seam, spec, **kw)
        split_secs = time.time() - t1
        t2 = time.time()
        single_art, single_rep = build_emulator(
            base_seam, spec, seam_split=False, **kw
        )
        single_secs = time.time() - t2
        band = dict(split_art.seam_band)

        # deterministic seam-crossing trace: log-uniform over the box,
        # fixed seed — it crosses the band by construction
        rng = np.random.default_rng(17)
        trace = np.stack([
            10 ** rng.uniform(np.log10(20.0), np.log10(600.0), n_trace),
            10 ** rng.uniform(np.log10(95.0), np.log10(105.0), n_trace),
        ], axis=1)

        def serve_trace(art, gated):
            svc = YieldService(
                art, base_seam, max_batch_size=256,
                error_gate_tol=None if gated else False,
            )
            vals = np.empty(n_trace)
            n_fb = n_gated = 0
            t0 = time.time()
            for lo in range(0, n_trace, 256):
                hi = min(lo + 256, n_trace)
                r = svc._evaluate_isolated(trace[lo:hi])
                vals[lo:hi] = r[0]
                n_fb += r[1]
                n_gated += r[5]
            seconds = time.time() - t0
            return vals, n_fb, n_gated, n_trace / max(seconds, 1e-9)

        v_sg, fb_sg, g_sg, qps_sg = serve_trace(split_art, gated=True)
        v_su, fb_su, g_su, qps_su = serve_trace(split_art, gated=False)
        v_1g, fb_1g, g_1g, qps_1g = serve_trace(single_art, gated=True)
        v_1u, fb_1u, g_1u, qps_1u = serve_trace(single_art, gated=False)

        # exact reference on a trace prefix, at the bundle's recorded
        # scheme (trapezoid — seam populations pin the reference scheme)
        static_seam = static_choices_from_config(base_seam)._replace(
            quad_panel_gl=bool(
                split_art.identity.get("quad_panel_gl", False)
            )
        )
        exact_eval = make_exact_evaluator(
            base_seam, static_seam, n_y=seam_ny, impl="tabulated",
            chunk_size=256,
        )
        exact_ref = exact_eval({
            "m_chi_GeV": trace[:n_ref, 0], "T_p_GeV": trace[:n_ref, 1],
        })["DM_over_B"]
        # gated answers (exact-fallback slots included) vs exact truth —
        # the acceptance number: gating keeps served answers <= 1e-3 off
        gated_rel = float(np.max(relative_errors(v_sg[:n_ref], exact_ref)))
        # and WITHOUT the gate/split, the single-domain surface serves
        # seam-adjacent queries wrong — the number the gate exists for
        ungated_single_rel = float(
            np.max(relative_errors(v_1u[:n_ref], exact_ref))
        )

        rate_sg = fb_sg / n_trace
        rate_1g = fb_1g / n_trace
        ratio = rate_1g / max(rate_sg, 1e-9)
        payload = {
            "metric": "seam_split_fallback_ratio",
            "value": round(ratio, 1),
            "unit": "x fewer exact fallbacks on a deterministic "
                    "seam-crossing trace (split+gated multi-domain "
                    "artifact vs single-domain at equal tolerance, "
                    "predicted-error gate on both)",
            "n_trace": n_trace,
            "seam_band": band,
            "rtol_target": seam_rtol,
            # serve trace, gated vs ungated, both artifacts
            "fallback_rate_split_gated": round(rate_sg, 4),
            "fallback_rate_split_ungated": round(fb_su / n_trace, 4),
            "fallback_rate_single_gated": round(rate_1g, 4),
            "fallback_rate_single_ungated": round(fb_1u / n_trace, 4),
            "gated_fallbacks_split": g_sg,
            "gated_fallbacks_single": g_1g,
            "qps_split_gated": round(qps_sg, 1),
            "qps_split_ungated": round(qps_su, 1),
            "qps_single_gated": round(qps_1g, 1),
            "qps_single_ungated": round(qps_1u, 1),
            # accuracy on the same line: gated answers vs exact, and the
            # wrong answers an ungated single-domain surface would serve
            "gated_vs_exact_max_rel_err": float(f"{gated_rel:.3e}"),
            "ungated_single_vs_exact_max_rel_err": float(
                f"{ungated_single_rel:.3e}"
            ),
            "n_exact_ref": n_ref,
            # build A/B at equal tolerance: exact-point budget + held-out
            "split_n_exact_evals": int(split_rep.n_exact_evals),
            "single_n_exact_evals": int(single_rep.n_exact_evals),
            "split_held_out_max_rel_err": float(
                f"{split_rep.max_rel_err:.3e}"
            ),
            "single_held_out_max_rel_err": float(
                f"{single_rep.max_rel_err:.3e}"
            ),
            "split_converged": bool(split_rep.converged),
            "single_converged": bool(single_rep.converged),
            "split_build_seconds": round(split_secs, 3),
            "single_build_seconds": round(single_secs, 3),
            "n_domains": len(split_art.domains),
            "bundle_hash": split_art.content_hash,
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "fallback_rate_split_gated",
                "fallback_rate_single_gated", "gated_vs_exact_max_rel_err",
                "split_n_exact_evals", "single_n_exact_evals",
                "split_held_out_max_rel_err", "single_held_out_max_rel_err",
                "split_converged",
            )
        }

    seam_split_summary = None
    try:
        seam_split_summary = run_leg("seam_split", seam_split_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] seam_split metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metrics: the LZ sweeps (BASELINE.json's metric name) --
    # Per-point P derived from a bounce profile through the two-channel
    # LZ kernel (the physics the reference only stubs) feeding the same
    # grid: total cost = LZ derivation + the sharded sweep.  Two legs:
    #   * "local"    — the analytic 1−e^(−2πλ₁/v) composition (cheapest)
    #   * "coherent" — the transfer-matrix kernel through the P(v_w)
    #     table + cubic 1/v interpolation the MCMC samples in-jit, with
    #     the table-build cost included (VERDICT r4 weak #3: the
    #     framework's headline physics deserves a measured cost, not
    #     just unit tests)
    # synthetic single-crossing profile (same family the LZ tests pin
    # against the analytic limit): Δ crosses zero at ξ = 0
    from bdlz_tpu.lz.profile import BounceProfile

    xi = np.linspace(-30.0, 30.0, 2001)
    lz_prof = BounceProfile(
        xi=xi,
        delta=-0.08 * np.tanh(xi / 4.0),
        mix=np.full_like(xi, 0.02),
    )
    # CPU fallback: a reduced fixed-size grid keeps the flagged legs
    # cheap after the relay wait (VERDICT r4 weak #4)
    n_lz = int(os.environ.get("BDLZ_BENCH_LZ_POINTS",
                              min(4096, n_total) if on_cpu else n_total))
    pp_lz_base = jax.tree.map(lambda a: np.asarray(a)[:n_lz], pp_all)

    _OMIT = object()  # "emit no vs_two_channel key" (the legacy legs)

    def lz_metric(metric_name, unit_detail, derive_P, extra=None,
                  baseline=_OMIT):
        t0 = time.time()
        P_lz = np.clip(np.asarray(derive_P(np.asarray(pp_lz_base.v_w))),
                       0.0, 1.0)
        t_derive = time.time() - t0
        pp_lz = pp_lz_base._replace(P=jnp.asarray(P_lz))
        run_lz = make_run_chunk(impl, reduce=pallas_reduce, pp=pp_lz)
        # warm-up + the shared spot-gate, on the SAME derived P
        lz_rel = accuracy_gate(run_lz, pp=pp_lz, static_run=static_for(impl))
        t1 = time.time()
        done = 0
        while done < n_lz:
            hi = min(done + chunk, n_lz)
            out = run_lz(done, hi)
            done = hi
        out.block_until_ready()
        lz_seconds = (time.time() - t1) + t_derive
        per_chip_lz = round(n_lz / lz_seconds / n_dev, 2)
        emit(
            {
                "metric": metric_name,
                "value": per_chip_lz,
                "unit": "param-points/sec/chip (%s + full pipeline, "
                        "n_y=%d)" % (unit_detail, n_y),
                "n_points": n_lz,
                "n_failed": None,
                "n_quarantined": None,
                "n_retries": None,
                "cache_hits": None,
                "cache_misses": None,
                "lz_derive_seconds": round(t_derive, 3),
                "seconds": round(lz_seconds, 3),
                "rel_err_vs_reference": float(f"{lz_rel:.3e}"),
                "impl": impl,
                "quad_impl": quad_impl_main,
                "n_quad_nodes": n_quad_main,
                "platform": jax.devices()[0].platform,
                "tpu_unavailable": tpu_unavailable,
                # scenario legs only: throughput vs the coherent
                # two-channel leg (the baseline both modes generalize;
                # null when that leg failed), plus the mode's
                # validation-gate residuals
                **({} if baseline is _OMIT else {
                    "vs_two_channel": (
                        round(per_chip_lz / baseline, 3)
                        if baseline else None
                    ),
                }),
                **(extra or {}),
            }
        )
        return per_chip_lz

    def lz_local_P(v_w):
        from bdlz_tpu.lz.sweep_bridge import probabilities_for_points

        return probabilities_for_points(lz_prof, v_w, method="local")

    def lz_coherent_P(v_w):
        # the MCMC's in-jit path: dense P(v_w) table from the coherent
        # transfer-matrix kernel, then cubic interpolation on the 1/v
        # grid — table-build cost lands in lz_derive_seconds
        from bdlz_tpu.lz.sweep_bridge import eval_P_table, make_P_of_vw_table

        table_n = int(os.environ.get("BDLZ_BENCH_LZ_TABLE_N",
                                     2048 if on_cpu else 0))  # 0 = default
        table = make_P_of_vw_table(
            lz_prof, "coherent",
            float(v_w.min()) * 0.99, min(float(v_w.max()) * 1.01, 1.0),
            n=table_n,
        )
        return eval_P_table(v_w, table, np)

    lz_per_chip = None
    lz_coherent_per_chip = None
    for attr, name, detail, derive in (
        ("lz_per_chip", "lz_sweep_points_per_sec_per_chip",
         "analytic LZ P(v_w) derivation", lz_local_P),
        ("lz_coherent_per_chip", "lz_coherent_sweep_points_per_sec_per_chip",
         "coherent transfer-matrix P(v_w) table build + interpolation",
         lz_coherent_P),
    ):
        try:
            val = run_leg(
                attr.replace("_per_chip", ""),
                lambda name=name, detail=detail, derive=derive: lz_metric(
                    name, detail, derive
                ),
            )
        except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
            print(f"[bench] {name} unavailable: {exc}", file=sys.stderr)
            val = None
        if attr == "lz_per_chip":
            lz_per_chip = val
        else:
            lz_coherent_per_chip = val

    # LZ scenario plane (docs/scenarios.md): the N-level chain and the
    # finite-T thermal-bath modes as measured production workloads —
    # same leg shape as the two-channel lines above, with each mode's
    # validation-gate residuals (bdlz_tpu.validation.chain_mode_audit /
    # thermal_mode_audit — a leg whose gate breaches never reports a
    # throughput) and the vs-two-channel throughput ratio on the line.
    n_chain_levels = int(os.environ.get("BDLZ_BENCH_LZ_N_LEVELS", 3))
    bath_eta = float(os.environ.get("BDLZ_BENCH_LZ_BATH_ETA", 0.05))
    bath_omega_c = float(os.environ.get("BDLZ_BENCH_LZ_BATH_OMEGA_C", 1.0))

    def lz_chain_metric():
        from bdlz_tpu.lz.chain import chain_probabilities_for_points
        from bdlz_tpu.validation import chain_mode_audit

        audit = chain_mode_audit(lz_prof, n_levels=n_chain_levels)
        if not audit.ok:
            raise RuntimeError(audit.reason)
        return lz_metric(
            "lz_chain_sweep_points_per_sec_per_chip",
            "N=%d banded-chain per-species P(v_w) derivation"
            % n_chain_levels,
            lambda v_w: chain_probabilities_for_points(
                lz_prof, v_w, n_chain_levels
            ),
            extra={
                "lz_mode": "chain",
                "lz_n_levels": n_chain_levels,
                "gate_n2_vs_coherent": float(
                    f"{audit.n2_vs_coherent:.3e}"
                ),
                "gate_analytic_flat_band": float(
                    f"{audit.analytic_flat_band:.3e}"
                ),
            },
            baseline=lz_coherent_per_chip,
        )

    def lz_thermal_metric():
        from bdlz_tpu.lz.thermal import thermal_probabilities_for_points
        from bdlz_tpu.validation import thermal_mode_audit

        audit = thermal_mode_audit(
            lz_prof, bath_eta, bath_omega_c, n_sample=8
        )
        if not audit.ok:
            raise RuntimeError(audit.reason)
        T_pts = np.asarray(pp_lz_base.T_p_GeV)
        return lz_metric(
            "lz_thermal_sweep_points_per_sec_per_chip",
            "finite-T bath Gamma_phi(T_p) derivation + dephased kernel",
            lambda v_w: thermal_probabilities_for_points(
                lz_prof, v_w, T_pts, bath_eta, bath_omega_c
            ),
            extra={
                "lz_mode": "thermal",
                "lz_bath_eta": bath_eta,
                "lz_bath_omega_c": bath_omega_c,
                "gate_cold_limit_bitwise": bool(audit.cold_limit_bitwise),
                "gate_monotonicity_defect": float(
                    audit.monotonicity_defect
                ),
            },
            baseline=lz_coherent_per_chip,
        )

    lz_chain_per_chip = None
    lz_thermal_per_chip = None
    for attr, name, fn in (
        ("lz_chain_per_chip",
         "lz_chain_sweep_points_per_sec_per_chip", lz_chain_metric),
        ("lz_thermal_per_chip",
         "lz_thermal_sweep_points_per_sec_per_chip", lz_thermal_metric),
    ):
        try:
            val = run_leg(attr.replace("_per_chip", ""), fn)
        except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
            print(f"[bench] {name} unavailable: {exc}", file=sys.stderr)
            val = None
        if attr == "lz_chain_per_chip":
            lz_chain_per_chip = val
        else:
            lz_thermal_per_chip = val

    # --- secondary metric: bounce_sweep (the in-framework O(4) bounce
    # solver, bdlz_tpu/bounce): potentials/sec/chip through the batched
    # fixed-lane-width shooting program, with the host scalar-loop A/B
    # on the line.  Gate-first like the scenario legs: the validation
    # gate (archived-P reproduction + thin-wall action) must pass before
    # any throughput is reported, and the batch/scalar-loop bitwise
    # parity contract is re-checked on the bench's own spec batch. ---
    def bounce_sweep_metric():
        from bdlz_tpu.bounce import (
            reference_potential,
            solve_bounce_batch,
            solve_bounce_scalar_loop,
        )
        from bdlz_tpu.validation import bounce_audit

        audit = bounce_audit()  # also warms the lane-width-8 program
        if not audit.ok:
            raise RuntimeError(audit.reason)
        n_bounce = int(os.environ.get("BDLZ_BENCH_BOUNCE_POINTS", 8))
        ref = reference_potential()
        # a vacuum-splitting scan around the reference point: ±10% eps
        # stays deep in the thin-wall regime, so every lane converges
        specs = [
            ref._replace(eps=float(e))
            for e in np.linspace(0.9, 1.1, n_bounce) * ref.eps
        ]
        t0 = time.time()
        batch = solve_bounce_batch(specs)
        t_batch = time.time() - t0
        t0 = time.time()
        loop = solve_bounce_scalar_loop(specs)
        t_loop = time.time() - t0
        for a, b in zip(batch, loop):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise RuntimeError(
                    "bounce batch vs scalar-loop parity breach on the "
                    "bench spec batch"
                )
        n_failed = int(np.count_nonzero(~np.asarray(batch.converged)))
        if n_failed:
            raise RuntimeError(
                f"{n_failed}/{n_bounce} bench bounce shoots failed to "
                "converge"
            )
        per_chip_bounce = round(n_bounce / t_batch / n_dev, 2)
        payload = {
            "metric": "bounce_profiles_per_sec_per_chip",
            "value": per_chip_bounce,
            "unit": "potentials/sec/chip (O(4) shoot: segment ladder + "
                    "bisection + dense action pass)",
            "n_points": n_bounce,
            "n_failed": n_failed,
            "n_quarantined": None,
            "n_retries": None,
            "cache_hits": None,
            "cache_misses": None,
            "seconds": round(t_batch, 3),
            # the A/B the tentpole claims: one vmapped lane-width-8
            # program filled by the batch vs the same program driven one
            # spec at a time from the host
            "scalar_loop_seconds": round(t_loop, 3),
            "vs_scalar_loop": (
                round(t_loop / t_batch, 2) if t_batch > 0 else None
            ),
            "gate_P_vs_archived": float(f"{audit.P_vs_archived:.3e}"),
            "gate_action_vs_thin_wall": float(
                f"{audit.action_vs_thin_wall:.3e}"
            ),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            "value": per_chip_bounce,
            "vs_scalar_loop": payload["vs_scalar_loop"],
            "gate_P_vs_archived": payload["gate_P_vs_archived"],
            "gate_action_vs_thin_wall": payload["gate_action_vs_thin_wall"],
        }

    bounce_summary = None
    try:
        bounce_summary = run_leg("bounce_sweep", bounce_sweep_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] bounce_sweep metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: serve_multitenant (scenario-routed pools) ---
    # The multi-tenant serving plane (bdlz_tpu/serve/tenancy.py) under a
    # deterministic fake-clock mixed-scenario trace: three pools —
    # the round's coherent artifact plus purpose-built N-level-chain and
    # finite-T thermal boxes — are cold-admitted from a provenance store
    # by content hash, pumped concurrently, then hit with a canned chaos
    # plan (replica faults confined to the chain pool via
    # fault_scenarios + one forced pool_evict mid-trace).  The evicted
    # pool answers a burst through the loud degraded exact path (reason
    # "pool_evicted"), is readmitted warm, and every non-degraded answer
    # must come back BIT-identical to a single-tenant fleet serving the
    # same artifact — routing, autoscaling and the evict/readmit cycle
    # may never buy a different answer.  The line carries availability,
    # QPS/chip, per-pool p50/p99 + shed rate, and the cold-admission /
    # readmit latency evidence.
    def serve_multitenant_metric(artifact):
        import dataclasses
        import tempfile

        from bdlz_tpu.emulator import AxisSpec, build_emulator
        from bdlz_tpu.provenance import Store, publish_artifact
        from bdlz_tpu.serve import REASON_POOL_EVICTED, MultiTenantService
        from bdlz_tpu.serve.fleet import FleetService
        from bdlz_tpu.serve.tenancy import pool_base

        mt_batch = int(os.environ.get("BDLZ_BENCH_MT_BATCH", 32))
        mt_ticks = max(8, int(os.environ.get("BDLZ_BENCH_MT_TICKS", 12)))
        mt_ny = int(os.environ.get("BDLZ_BENCH_MT_NY", 400))
        mt_nodes = int(os.environ.get("BDLZ_BENCH_MT_GRID", 3))
        mt_levels = int(os.environ.get("BDLZ_BENCH_MT_CHAIN_LEVELS", 5))
        scenarios = ("coherent", "chain", "thermal")

        # the two scenario boxes share the coherent leg's build base and
        # differ ONLY in the scenario knobs — the tenancy plane's strict
        # per-pool identity check demands exactly that
        base_chain = dataclasses.replace(
            base, lz_mode="chain", lz_n_levels=mt_levels
        )
        base_thermal = dataclasses.replace(
            base, lz_mode="thermal", lz_bath_eta=bath_eta,
            lz_bath_omega_c=bath_omega_c,
        )
        build_kw = dict(
            rtol=1e-2, n_probe=4, n_holdout=8, max_rounds=1, n_y=mt_ny,
            chunk_size=64, require_converged=False, lz_profile=lz_prof,
        )
        t_build = time.time()
        art_chain, _ = build_emulator(
            base_chain,
            {"m_chi_GeV": AxisSpec(0.9, 1.1, 2, "log"),
             "v_w": AxisSpec(0.25, 0.35, mt_nodes, "lin")},
            **build_kw,
        )
        art_thermal, _ = build_emulator(
            base_thermal,
            {"T_p_GeV": AxisSpec(90.0, 110.0, 2, "log"),
             "v_w": AxisSpec(0.25, 0.35, mt_nodes, "lin")},
            **build_kw,
        )
        build_seconds = time.time() - t_build
        arts = {"coherent": artifact, "chain": art_chain,
                "thermal": art_thermal}

        # per-scenario request streams drawn inside each pool's hull
        rng = np.random.default_rng(23)
        thetas_of, cursor = {}, {}
        for scn, art in arts.items():
            lo = np.array([nodes[0] for nodes in art.axis_nodes])
            hi = np.array([nodes[-1] for nodes in art.axis_nodes])
            thetas_of[scn] = rng.uniform(
                lo, hi, size=(mt_ticks * mt_batch, len(lo))
            )
            cursor[scn] = 0

        # canned chaos plan: replica-1 faults confined to the CHAIN pool
        # (fault_scenarios), plus one forced eviction (key 0 = the first
        # eviction-counter value; it defers until a pool is provably
        # idle — the trace makes that the coherent pool, mid-trace)
        plan_obj = {"faults": [
            {"site": "replica_dispatch", "kind": "transient", "key": 1,
             "times": 2},
            {"site": "replica_dispatch", "kind": "nan", "key": 1,
             "times": 1},
            {"site": "pool_evict", "kind": "raise", "key": 0},
        ]}

        class _Tick:
            t = 0.0

            def __call__(self):
                return self.t

        # gate off + tight breaker knobs, exactly the chaos_serve
        # rationale: the bitwise pin compares pure replica-kernel
        # answers, and one bad batch must trip/heal INSIDE the trace
        scfg = dataclasses.replace(
            base, breaker_window=1, breaker_cooldown_s=0.05,
            error_gate_tol=False,
        )
        ta = mt_ticks // 2          # all three pools busy
        tb = max(2, mt_ticks // 4)  # coherent dark: evict + degraded
        per_pool = {}
        with tempfile.TemporaryDirectory() as mt_root:
            store = Store(os.path.join(mt_root, "store"))
            tenant_map = {
                scn: publish_artifact(store, art)
                for scn, art in arts.items()
            }
            tick = _Tick()
            t_trace = time.time()
            svc = MultiTenantService(
                scfg, tenant_map=tenant_map, store=store,
                max_batch_size=mt_batch, n_replicas=2, clock=tick,
                max_wait_s=1e-3, fault_plan=json.dumps(plan_obj),
                fault_scenarios=("chain",), error_gate_tol=False,
                lz_profile=lz_prof, replica_budget=8,
                autoscale_interval_s=0.05,
            )
            futs = []

            def burst(scn):
                i = cursor[scn]
                cursor[scn] = i + mt_batch
                for k in range(i, i + mt_batch):
                    futs.append(
                        (scn, k, svc.submit(thetas_of[scn][k], scenario=scn))
                    )

            for t in range(mt_ticks):
                if t == ta + tb:
                    # warm readmission through the cold-admission path
                    svc.readmit("coherent")
                if t < ta or t >= ta + tb or t == ta + 1:
                    # t == ta: coherent goes dark (idle -> the forced
                    # eviction's victim); t == ta + 1: one burst lands
                    # on the evicted pool's degraded queue
                    burst("coherent")
                burst("chain")
                burst("thermal")
                # advance BEFORE dispatch so per-request latency is a
                # nonzero deterministic function of the trace
                tick.t += 0.02
                svc.run_once()
                svc.poll(block=True)
                if t == ta and not svc.pool("coherent").evicted:
                    raise RuntimeError(
                        "forced pool_evict did not fire at the idle tick"
                    )
            svc.drain()
            trace_seconds = time.time() - t_trace

            n_req = len(futs)
            answered = 0
            degraded_answers = 0
            mt_vals = {
                scn: np.full(cursor[scn], np.nan) for scn in scenarios
            }
            exact_ok = {
                scn: np.zeros(cursor[scn], dtype=bool) for scn in scenarios
            }
            for scn, k, f in futs:
                try:
                    resp = f.result(timeout=0)
                except Exception:  # noqa: BLE001 — availability counts these
                    continue
                answered += 1
                if resp.degraded:
                    if resp.fallback_reason == REASON_POOL_EVICTED:
                        degraded_answers += 1
                else:
                    mt_vals[scn][k] = resp.value
                    exact_ok[scn][k] = True
            availability = answered / n_req
            summary = svc.summary()
            n_devices = max(
                p.fleet.replica_set.n_devices
                for p in svc.pools.values() if p.fleet is not None
            )
            admissions = list(svc.admission_events)
            svc.close()

            # the single-tenant control fleets: same artifacts, same
            # per-pool configs, no faults — every non-degraded answer
            # must match them bit-for-bit
            bitwise = True
            for scn, art in arts.items():
                rcfg = dataclasses.replace(
                    pool_base(scfg, art),
                    fault_plan=None, fault_injection=False,
                )
                ref = FleetService(
                    art, rcfg, max_batch_size=mt_batch, n_replicas=1,
                    max_wait_s=1e-3,
                    lz_profile=lz_prof if scn != "coherent" else None,
                )
                rfuts = [
                    ref.submit(th) for th in thetas_of[scn][:cursor[scn]]
                ]
                ref.drain()
                ref_vals = np.array(
                    [f.result(timeout=0).value for f in rfuts]
                )
                ref.close()
                ok = exact_ok[scn]
                bitwise = bitwise and bool(
                    np.array_equal(mt_vals[scn][ok], ref_vals[ok])
                )

        cold_admission_s = {
            ev["scenario"]: round(ev["seconds"], 4)
            for ev in admissions if not ev["readmit"]
        }
        readmit_s = next(
            (round(ev["seconds"], 4) for ev in admissions if ev["readmit"]),
            None,
        )
        for content_hash, s in summary["pools"].items():
            per_pool[s["scenario"]] = {
                "artifact_hash": content_hash,
                "lz_mode": s["lz_mode"],
                "n_replicas": s["n_replicas"],
                "evicted": s["evicted"],
                "accepted": s["accepted"],
                "shed_rate": s["shed_rate"],
                "p50_latency_s": s["p50_latency_s"],
                "p99_latency_s": s["p99_latency_s"],
                "mean_occupancy": s["mean_occupancy"],
            }
        serve_seconds = max(
            trace_seconds - sum(ev["seconds"] for ev in admissions), 1e-9
        )
        qps_per_chip = round(answered / serve_seconds / n_devices, 1)
        payload = {
            "metric": "serve_multitenant_availability",
            "value": round(availability, 4),
            "unit": "answered fraction across %d scenario pools under a "
                    "canned chaos plan (chain-pool replica faults + one "
                    "forced eviction, fake-clock trace, batch %d)"
                    % (len(scenarios), mt_batch),
            "n_requests": n_req,
            "n_pools": len(scenarios),
            "scenarios": list(scenarios),
            "qps_per_chip": qps_per_chip,
            "per_pool": per_pool,
            "shed_rate": max(
                p["shed_rate"] for p in per_pool.values()
            ),
            "cold_admission_s": cold_admission_s,
            "readmit_s": readmit_s,
            "degraded_answers": degraded_answers,
            "evictions": summary["evictions"],
            "forced_evictions": summary["forced_evictions"],
            "admissions": summary["admissions"],
            "readmissions": summary["readmissions"],
            "autoscale_passes": summary["autoscale_passes"],
            "resizes": summary["resizes"],
            "replica_budget": summary["replica_budget"],
            "tenant_routing": summary["tenant_routing"],
            "bitwise_equal_unaffected": bitwise,
            "fault_plan": plan_obj["faults"],
            "build_seconds": round(build_seconds, 3),
            "wall_seconds": round(trace_seconds, 4),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "qps_per_chip", "shed_rate", "cold_admission_s",
                "readmit_s", "degraded_answers", "forced_evictions",
                "autoscale_passes", "bitwise_equal_unaffected",
            )
        }

    multitenant_summary = None
    try:
        _mt_hit = leg_lookup("serve_multitenant")
        if _mt_hit is not None:
            multitenant_summary = _mt_hit.get("summary")
        elif emu_artifact is None:
            print("[bench] serve_multitenant skipped: no emulator artifact "
                  "this round", file=sys.stderr)
        else:
            multitenant_summary = run_leg(
                "serve_multitenant",
                lambda: serve_multitenant_metric(emu_artifact),
            )
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] serve_multitenant metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: cross-host fabric availability under a ------
    # whole-host kill (ISSUE-20).  A 2-host in-process ServingFabric
    # serves one tenant off a shared store; a canned host_crash fault
    # kills host 0 mid-trace.  The contract measured: queued requests on
    # the corpse fail TYPED (never silent) and client retries re-answer
    # through the submit ladder on the survivor, which cold-admits the
    # tenant by content hash through its pull-through cache (a fetch,
    # never a rebuild); availability must stay >= 0.99 and every
    # unaffected answer must be bitwise-equal to a clean single-host
    # fleet over the same thetas.
    def serve_crosshost_metric(artifact):
        import dataclasses
        import tempfile

        from bdlz_tpu.provenance import Store, publish_artifact
        from bdlz_tpu.serve import (
            FabricHost,
            GlobalRouter,
            ServiceUnavailable,
            ServingFabric,
        )
        from bdlz_tpu.serve.fleet import FleetService
        from bdlz_tpu.serve.tenancy import pool_base

        xh_batch = int(os.environ.get("BDLZ_BENCH_XH_BATCH", 16))
        xh_ticks = max(8, int(os.environ.get("BDLZ_BENCH_XH_TICKS", 12)))
        xh_ttl = float(os.environ.get("BDLZ_BENCH_XH_TTL_S", 0.06))
        kill_tick = max(2, xh_ticks // 3)

        # the canned churn: host 0 dies at its kill_tick-th fabric tick
        plan_obj = {"faults": [
            {"site": "host_crash", "kind": "raise", "key": kill_tick},
        ]}

        class _Tick:
            t = 0.0

            def __call__(self):
                return self.t

        lo = np.array([nodes[0] for nodes in artifact.axis_nodes])
        hi = np.array([nodes[-1] for nodes in artifact.axis_nodes])
        rng = np.random.default_rng(29)
        n_req = xh_ticks * xh_batch
        thetas = rng.uniform(lo, hi, size=(n_req, len(lo)))

        scfg = dataclasses.replace(base, error_gate_tol=False)
        answered = {}
        pending = []
        retry = []
        typed_losses = 0
        untyped_losses = 0
        t_crash = None
        first_survivor_t = None

        with tempfile.TemporaryDirectory() as xh_root:
            store = Store(os.path.join(xh_root, "store"))
            content_hash = publish_artifact(store, artifact)
            tick = _Tick()
            hosts = [
                FabricHost(
                    scfg, fabric="bench", host_id=f"h{i}", host_index=i,
                    store=store, tenant_map={"coherent": content_hash},
                    clock=tick, ttl_s=xh_ttl,
                    cache_root=os.path.join(xh_root, f"cache{i}"),
                    fault_plan=json.dumps(plan_obj) if i == 0 else None,
                    max_batch_size=xh_batch, max_wait_s=1e-3,
                    n_replicas=1,
                )
                for i in range(2)
            ]
            fab = ServingFabric(
                hosts, GlobalRouter(store, "bench", 2, clock=tick)
            )
            fab.register_all()

            def _submit(k):
                try:
                    pending.append(
                        (k, fab.submit(thetas[k], scenario="coherent"))
                    )
                except ServiceUnavailable:
                    retry.append(k)  # no live host this instant

            def _collect():
                nonlocal typed_losses, untyped_losses, first_survivor_t
                still = []
                for k, f in pending:
                    if not f.done():
                        still.append((k, f))
                        continue
                    try:
                        resp = f.result(timeout=0)
                    except ServiceUnavailable:
                        # the whole availability story: loss is TYPED,
                        # so the client can retry through the ladder
                        typed_losses += 1
                        retry.append(k)
                    except Exception:  # noqa: BLE001 — silent-loss audit
                        untyped_losses += 1
                    else:
                        answered[k] = resp
                        if (
                            resp.host_id == "h1"
                            and t_crash is not None
                            and first_survivor_t is None
                        ):
                            first_survivor_t = tick.t
                pending[:] = still

            t_trace = time.time()
            cursor = 0
            for t in range(xh_ticks):
                resubmits, retry[:] = list(retry), []
                for k in resubmits:
                    _submit(k)
                for k in range(cursor, cursor + xh_batch):
                    _submit(k)
                cursor += xh_batch
                tick.t += 0.02
                fab.tick()
                if t_crash is None and not hosts[0].alive:
                    t_crash = tick.t
                _collect()
            for _ in range(6):  # drain + retry rounds for the tail
                fab.drain()
                _collect()
                if not retry and not pending:
                    break
                resubmits, retry[:] = list(retry), []
                for k in resubmits:
                    _submit(k)
                tick.t += 0.02
                fab.tick()
            trace_seconds = time.time() - t_trace

            availability = len(answered) / n_req
            summary = fab.summary()
            survivor_adm = list(hosts[1].service.admission_events)
            survivor_cache = hosts[1].artifact_cache.counters()
            by_host = {
                hid: sum(1 for r in answered.values() if r.host_id == hid)
                for hid in ("h0", "h1")
            }
            fab.close()

            # the clean control fleet: same artifact, same config, no
            # faults, one host — every answer must match bit-for-bit
            rcfg = dataclasses.replace(
                pool_base(scfg, artifact),
                fault_plan=None, fault_injection=False,
            )
            ref = FleetService(
                artifact, rcfg, max_batch_size=xh_batch, n_replicas=1,
                max_wait_s=1e-3,
            )
            rfuts = [ref.submit(th) for th in thetas]
            ref.drain()
            ref_vals = np.array(
                [f.result(timeout=0).value for f in rfuts]
            )
            ref.close()
            got = np.array([
                answered[k].value if k in answered else np.nan
                for k in range(n_req)
            ])
            ok = np.array([k in answered for k in range(n_req)])
            bitwise = bool(np.array_equal(got[ok], ref_vals[ok]))

        failover_s = (
            None if first_survivor_t is None or t_crash is None
            else round(first_survivor_t - t_crash, 4)
        )
        payload = {
            "metric": "serve_crosshost_availability",
            "value": round(availability, 4),
            "unit": "answered fraction on a 2-host fabric with host 0 "
                    "killed at fabric tick %d (typed-loss client "
                    "retries, fake-clock trace, batch %d)"
                    % (kill_tick, xh_batch),
            "n_requests": n_req,
            "n_hosts": 2,
            "kill_tick": kill_tick,
            "host_lease_ttl_s": xh_ttl,
            "typed_losses": typed_losses,
            "untyped_losses": untyped_losses,
            "failovers": summary["failovers"],
            "failover_latency_s": failover_s,
            "answered_by": by_host,
            "survivor_admissions": len(survivor_adm),
            "survivor_cache": survivor_cache,
            "readmit_was_fetch": bool(
                len(survivor_adm) == 1
                and not survivor_adm[0]["readmit"]
                and survivor_cache["misses"] == 1
            ),
            "bitwise_equal_unaffected": bitwise,
            "fault_plan": plan_obj["faults"],
            "wall_seconds": round(trace_seconds, 4),
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        }
        emit(payload)
        return {
            k: payload[k] for k in (
                "value", "typed_losses", "untyped_losses", "failovers",
                "failover_latency_s", "survivor_admissions",
                "readmit_was_fetch", "bitwise_equal_unaffected",
            )
        }

    crosshost_summary = None
    try:
        _xh_hit = leg_lookup("serve_crosshost")
        if _xh_hit is not None:
            crosshost_summary = _xh_hit.get("summary")
        elif emu_artifact is None:
            print("[bench] serve_crosshost skipped: no emulator artifact "
                  "this round", file=sys.stderr)
        else:
            crosshost_summary = run_leg(
                "serve_crosshost",
                lambda: serve_crosshost_metric(emu_artifact),
            )
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] serve_crosshost metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: the closed-loop self-improving service ------
    # ROADMAP item 4's acceptance instrument (bdlz_tpu/refine/): a
    # deliberately NARROW seed emulator serves a replayed deterministic
    # two-hour mixed trace (fake clock — each hour is 3600 fake-clock
    # seconds) whose request distribution hangs half outside the box.
    # The refinement daemon detects the drift from the armed per-query
    # trace, persists the content-hashed traffic snapshot, rebuilds over
    # the traffic-expanded box as elastic chunks steered by
    # refine_signal="traffic", and the delivery pipeline auto-publishes
    # the winner — zero operator action.  The line records hour-1 vs
    # hour-2 gated-fallback rates (hour 2 must be lower after the ONE
    # autonomous rebuild+rollout cycle) and the bitwise pin on a
    # far-out-of-domain probe whose exact-fallback answer must be
    # bit-identical before and after the rollout (unaffected regions
    # never change under self-improvement).
    def self_improve_metric():
        import dataclasses as _dc  # noqa: F401 — config replaces below
        import shutil
        import tempfile

        from bdlz_tpu.emulator.build import AxisSpec, build_emulator
        from bdlz_tpu.provenance import Store
        from bdlz_tpu.refine import RefinementDaemon
        from bdlz_tpu.serve.fleet import FleetService

        n_req = int(os.environ.get("BDLZ_BENCH_SI_QUERIES", 256))  # /hour
        si_batch = max(
            1, min(int(os.environ.get("BDLZ_BENCH_SI_BATCH", 8)), n_req)
        )
        si_ny = int(os.environ.get("BDLZ_BENCH_SI_NY", 200))
        n_batches = max(1, n_req // si_batch)
        dt = 3600.0 / n_batches  # one fake-clock hour per trace half

        class _Tick:
            t = 0.0

            def __call__(self):
                return self.t

        tmp_store = tempfile.mkdtemp(prefix="bdlz_bench_refine_")
        t_si = time.time()
        try:
            store = Store(tmp_store)
            # the narrow seed box the traffic has drifted out of
            seed_spec = {
                "m_chi_GeV": AxisSpec(0.9, 1.0, 3, "log"),
                "T_p_GeV": AxisSpec(90.0, 100.0, 3, "log"),
            }
            build_kw = dict(n_probe=6, max_rounds=2, n_y=si_ny,
                            rtol=1e-3, chunk_size=16)
            seed_art, _ = build_emulator(
                base, seed_spec, cache=store, **build_kw
            )
            tick = _Tick()
            svc = FleetService(
                seed_art, base, max_batch_size=si_batch, n_replicas=2,
                routing="round_robin", max_wait_s=1e-3, clock=tick,
            )
            daemon = RefinementDaemon(
                svc, base, store=store, clock=tick,
                window=n_req, min_queries=min(32, max(8, n_req // 4)),
                drift_gated_rate=0.05, rebuild_budget=1,
                observe_s=2.0 * dt, build_kw=build_kw, elastic=2,
            )
            rng = np.random.default_rng(7)
            # mixed drifted distribution: ~half the mass outside the box
            lo = np.array([0.95, 95.0])
            hi = np.array([1.08, 108.0])
            far_ood = np.array([2.0, 150.0])

            def serve_block(thetas):
                futs = [svc.submit(t) for t in np.atleast_2d(thetas)]
                tick.t += dt
                svc.run_once(force=True)
                svc.poll(block=True)
                return [f.result() for f in futs]

            def hour():
                start = len(svc.stats.rows)
                for _ in range(n_batches):
                    serve_block(rng.uniform(lo, hi, (si_batch, 2)))
                    daemon.step()
                rows = svc.stats.rows[start:]
                n = sum(r.size for r in rows)
                return {
                    "gated_fallback_rate": round(
                        sum(r.n_fallback for r in rows) / n, 4
                    ),
                    "gated_rate": round(
                        sum(r.n_gated for r in rows) / n, 4
                    ),
                    "n_requests": n,
                }

            far_before = serve_block(far_ood)[0]
            h1 = hour()
            h2 = hour()
            far_after = serve_block(far_ood)[0]
            bitwise = (
                np.float64(far_before.value).tobytes()
                == np.float64(far_after.value).tobytes()
            )
            history = daemon.history
            decision = history[0]["decision"] if history else None
            si_seconds = time.time() - t_si
            payload = {
                "metric": "self_improve_gated_rate",
                "value": h2["gated_fallback_rate"],
                "unit": "gated-fallback fraction (ood + error-gated) of "
                        "hour 2 of a replayed two-hour drifted trace, "
                        "after one autonomous traffic-steered "
                        "rebuild+rollout cycle (hour 1: %.4f)"
                        % h1["gated_fallback_rate"],
                "n_requests": 2 * n_batches * si_batch + 2,
                "batch": si_batch,
                "gated_fallback_hour1": h1["gated_fallback_rate"],
                "gated_fallback_hour2": h2["gated_fallback_rate"],
                "gated_rate_hour1": h1["gated_rate"],
                "gated_rate_hour2": h2["gated_rate"],
                "cycles": daemon.cycles,
                "daemon_state": daemon.state,
                "drift_gated_rate": daemon.drift_gated_rate,
                "rebuild_budget": daemon.rebuild_budget,
                "snapshot": history[0]["snapshot"] if history else None,
                "train_snapshot": (
                    history[0]["train_snapshot"] if history else None
                ),
                "decision": (
                    {k: decision[k] for k in (
                        "outcome", "candidate_score", "serving_score",
                    )} if decision else None
                ),
                "seed_hash": seed_art.content_hash,
                "serving_hash": svc.artifact_hash,
                "elastic": True,
                "n_y": si_ny,
                "bitwise_equal_unaffected": bool(bitwise),
                "n_failed": None,
                "n_quarantined": None,
                "n_retries": None,
                "cache_hits": None,
                "cache_misses": None,
                "wall_seconds": round(si_seconds, 4),
                "platform": jax.devices()[0].platform,
                "tpu_unavailable": tpu_unavailable,
            }
            emit(payload)
            return {
                k: payload[k] for k in (
                    "value", "gated_fallback_hour1", "gated_fallback_hour2",
                    "cycles", "daemon_state", "bitwise_equal_unaffected",
                )
            }
        finally:
            shutil.rmtree(tmp_store, ignore_errors=True)

    self_improve_summary = None
    try:
        self_improve_summary = run_leg("self_improve", self_improve_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] self_improve metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: the differentiable pipeline (grad_sweep) ----
    # d(Ω_DM/Ω_b)/dθ throughput through jax.grad of the exact pipeline
    # (sampling/grad.py — the gradient layer NUTS and the Fisher-aware
    # emulator refinement ride), with a finite-difference parity spot
    # check of the Planck log-posterior gradient on the SAME line: the
    # acceptance number (rel err ≤ 1e-5) is measured every round, not
    # only in unit tests.
    def grad_sweep_metric():
        from bdlz_tpu.sampling import (
            gradient_parity,
            make_pipeline_logprob,
            make_pipeline_observables,
            make_ratio_and_grad,
        )

        n_grad = int(os.environ.get(
            "BDLZ_BENCH_GRAD_POINTS",
            min(4096, n_total) if on_cpu else n_total,
        ))
        gchunk = min(int(os.environ.get("BDLZ_BENCH_GRAD_CHUNK", 1024)),
                     n_grad)
        n_grad = (n_grad // gchunk) * gchunk
        param_keys = ("m_chi_GeV", "T_p_GeV", "P_chi_to_B", "v_w")
        st_g = static_for("tabulated")
        obs = make_pipeline_observables(
            base, st_g, table, param_keys=param_keys, n_y=n_y,
        )
        ratio_grad = make_ratio_and_grad(obs)
        rng = np.random.default_rng(11)
        thetas = np.stack([
            10 ** rng.uniform(-1.0, 1.0, n_grad),
            10 ** rng.uniform(np.log10(30.0), np.log10(300.0), n_grad),
            rng.uniform(0.02, 0.9, n_grad),
            rng.uniform(0.05, 0.9, n_grad),
        ], axis=1)

        def sweep(fn):
            out = None
            for lo in range(0, n_grad, gchunk):
                out = fn(jnp.asarray(thetas[lo:lo + gchunk]))
            jax.block_until_ready(out)

        forward = jax.jit(jax.vmap(
            lambda t: obs(t)[1] / obs(t)[0]
        ))
        sweep(ratio_grad)              # compile warm-up (one chunk shape)
        t0 = time.time()
        sweep(ratio_grad)
        g_seconds = time.time() - t0
        sweep(forward)
        t1 = time.time()
        sweep(forward)
        f_seconds = time.time() - t1
        g_pps = round(n_grad / max(g_seconds, 1e-9) / n_dev, 2)
        f_pps = round(n_grad / max(f_seconds, 1e-9) / n_dev, 2)

        # FD parity spot check at a deterministic in-bounds point — the
        # tentpole's acceptance criterion, on the metric line itself
        logp = make_pipeline_logprob(
            base, st_g, table, param_keys=("m_chi_GeV", "P_chi_to_B"),
            bounds={"m_chi_GeV": (0.05, 20.0), "P_chi_to_B": (1e-4, 1.0)},
            n_y=n_y,
        )
        parity = gradient_parity(logp, np.array([0.97, 0.15]))

        emit({
            "metric": "grad_sweep_points_per_sec_per_chip",
            "value": g_pps,
            "unit": "d(Omega_DM/Omega_b)/dtheta points/sec/chip "
                    "(reverse-mode, %d params, n_y=%d)"
                    % (len(param_keys), n_y),
            "n_points": n_grad,
            "n_params": len(param_keys),
            "n_failed": None,
            "n_quarantined": None,
            "n_retries": None,
            "cache_hits": None,
            "cache_misses": None,
            "seconds": round(g_seconds, 3),
            "forward_points_per_sec_per_chip": f_pps,
            "vs_forward": round(g_pps / max(f_pps, 1e-9), 3),
            "fd_max_rel_err": float(f"{parity['max_rel_err']:.3e}"),
            "impl": "tabulated",
            "quad_impl": quad_impl_main,
            "n_quad_nodes": n_quad_main,
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        })
        return {
            "value": g_pps,
            "vs_forward": round(g_pps / max(f_pps, 1e-9), 3),
            "fd_max_rel_err": float(f"{parity['max_rel_err']:.3e}"),
        }

    grad_sweep_summary = None
    try:
        grad_sweep_summary = run_leg("grad_sweep", grad_sweep_metric)
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] grad_sweep metric unavailable: {exc}",
              file=sys.stderr)

    # --- secondary metric: NUTS vs stretch ESS per logp evaluation ----
    # The convergence-per-FLOP claim of the gradient sampler, measured
    # on the Planck posterior over the round's emulator artifact (the
    # science loop's fast mode): both samplers run the SAME posterior,
    # both chains are scored with the SAME rank-normalized bulk-ESS
    # instrument (sampling/diagnostics.py), and each divides by every
    # logp evaluation it made — NUTS counts each leapfrog step AND its
    # warmup bill, the stretch counts every walker proposal.
    def nuts_ess_metric(artifact):
        from bdlz_tpu.sampling import (
            bulk_ess,
            make_pipeline_logprob,
            run_ensemble,
            run_nuts,
        )

        W = int(os.environ.get("BDLZ_BENCH_NUTS_WALKERS", 32))
        st_steps = int(os.environ.get("BDLZ_BENCH_NUTS_STRETCH_STEPS", 512))
        n_chains = int(os.environ.get("BDLZ_BENCH_NUTS_CHAINS", 4))
        n_steps = int(os.environ.get("BDLZ_BENCH_NUTS_STEPS", 384))
        n_warm = int(os.environ.get("BDLZ_BENCH_NUTS_WARMUP", 200))
        mass = os.environ.get("BDLZ_BENCH_NUTS_MASS", "diag")
        # (log10 m_chi, sigma_y): both directions genuinely constrained
        # by the two Planck Gaussians (Omega_DM pins the mass, Omega_b
        # pins the source width) — a compact posterior, so the A/B
        # measures sampler quality, not prior-wall truncation.  T_p is
        # deliberately NOT sampled: the source integral makes logp
        # exactly flat in T_p over a wide range (measured), and a flat
        # direction against hard prior walls measures the box, not the
        # kernel.  Mass is sampled in log10 (the pipeline is near
        # power-law there — the posterior is near-Gaussian, which is
        # the geometry NUTS's mass adaptation expects).
        param_keys = ("m_chi_GeV", "source_shape_sigma_y")
        bounds = {
            "m_chi_GeV": (np.log10(0.2), np.log10(5.0)),
            "source_shape_sigma_y": (4.0, 16.0),
        }
        logp = make_pipeline_logprob(
            base, static, table, param_keys=param_keys, bounds=bounds,
            log_params=("m_chi_GeV",), emulator=artifact,
        )
        k0 = jax.random.PRNGKey(1234)
        center = np.array([np.log10(0.9), 9.0])
        spread = np.array([0.01, 0.1])

        def init_for(n):
            return center + spread * np.asarray(
                jax.random.normal(jax.random.fold_in(k0, n), (n, 2))
            )

        # stretch: the incumbent — every step evaluates one proposal per
        # walker, plus the W initial evaluations
        st_run = run_ensemble(
            jax.random.PRNGKey(77), logp, init_for(W), n_steps=st_steps,
        )
        st_burn = st_steps // 4
        st_chain = np.asarray(st_run.chain[st_burn:])
        st_ess = float(np.min(bulk_ess(st_chain)))
        st_evals = W * st_steps + W
        st_eff = st_ess / st_evals

        # NUTS: vmapped chains, dense/diag mass + dual averaging per the
        # knobs; the eval counter includes warmup and the ε searches
        nuts_run = run_nuts(
            jax.random.PRNGKey(78), logp, init_for(n_chains),
            n_steps=n_steps, n_warmup=n_warm, mass_matrix=mass,
        )
        nuts_chain = np.asarray(nuts_run.chain)
        nuts_ess = float(np.min(bulk_ess(nuts_chain)))
        nuts_eff = nuts_ess / nuts_run.n_logp_evals
        ratio = nuts_eff / max(st_eff, 1e-300)

        emit({
            "metric": "nuts_ess_per_eval",
            "value": round(ratio, 2),
            "unit": "NUTS vs stretch bulk-ESS per logp evaluation "
                    "(Planck posterior, emulator-backed, min over params)",
            "params": list(param_keys),
            "nuts_ess": round(nuts_ess, 1),
            "nuts_evals": int(nuts_run.n_logp_evals),
            "nuts_ess_per_eval": float(f"{nuts_eff:.4e}"),
            "nuts_step_size": float(f"{nuts_run.step_size:.4e}"),
            "nuts_divergent": int(nuts_run.n_divergent),
            "nuts_mean_tree_depth": round(nuts_run.mean_tree_depth, 2),
            "mass_matrix": mass,
            "n_chains": n_chains,
            "n_steps": n_steps,
            "n_warmup": n_warm,
            "stretch_ess": round(st_ess, 1),
            "stretch_evals": int(st_evals),
            "stretch_ess_per_eval": float(f"{st_eff:.4e}"),
            "stretch_acceptance": round(float(st_run.acceptance), 4),
            "n_walkers": W,
            "stretch_steps": st_steps,
            "artifact_hash": artifact.content_hash,
            "platform": jax.devices()[0].platform,
            "tpu_unavailable": tpu_unavailable,
        })
        return {
            "value": round(ratio, 2),
            "nuts_ess_per_eval": float(f"{nuts_eff:.4e}"),
            "stretch_ess_per_eval": float(f"{st_eff:.4e}"),
            "mass_matrix": mass,
            "nuts_divergent": int(nuts_run.n_divergent),
        }

    nuts_summary = None
    try:
        _nuts_hit = leg_lookup("nuts_ess")
        if _nuts_hit is not None:
            nuts_summary = _nuts_hit.get("summary")
        elif emu_artifact is None:
            # no fresh artifact this round (emulator leg failed, or a
            # cache hit without a matching nuts entry): nothing to sample
            print("[bench] nuts_ess_per_eval skipped: no emulator "
                  "artifact this round", file=sys.stderr)
        else:
            nuts_summary = run_leg(
                "nuts_ess", lambda: nuts_ess_metric(emu_artifact)
            )
    except Exception as exc:  # noqa: BLE001 — secondary metric is best-effort
        print(f"[bench] nuts_ess_per_eval metric unavailable: {exc}",
              file=sys.stderr)

    # main metric LAST (the driver parses the final line)
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "param-points/sec/chip (full pipeline, n_y=%d)" % n_y,
                "vs_baseline": round(per_chip / 4.3, 1),
                "n_points": n_total,
                "n_devices": n_dev,
                # robustness schema (nulls: the timed loop discards chunk
                # outputs, and healing only engages via run_sweep — the
                # chaos line below carries the measured counters)
                "n_failed": None,
                "n_quarantined": None,
                "n_retries": None,
                # provenance schema: the timed loop bypasses the chunk
                # cache by design (a cached headline number is not a
                # throughput measurement); the sweep_cache line carries
                # the real counters
                "cache_hits": None,
                "cache_misses": None,
                # the main MEASUREMENT (gates + timed sweep) was reused
                # from a prior round's leg-cache entry — only ever true
                # on a tpu_unavailable round with identical code/knobs
                **({"cached": True} if main_cached else {}),
                "seconds": round(seconds, 3),
                "rel_err_vs_reference": (
                    None if max_rel is None else float(f"{max_rel:.3e}")
                ),
                **({"gate_error": gate_error} if gate_error else {}),
                "gate_points": n_gate,
                "impl": impl,
                # the y-quadrature the MAIN timed engine ran with, plus
                # the per-round panel-GL A/B summary (null = A/B leg
                # failed; its secondary line carries the full detail)
                "quad_impl": quad_impl_main,
                "n_quad_nodes": n_quad_main,
                "quad_gl": quad_gl_summary,
                # self-describing when the PALLAS path ran at an
                # explicitly-set or non-default kernel block (the
                # collector's COL_BLOCK sweep, incl. its 8 leg); absent
                # off the pallas path like pallas_reduce
                **(pallas_evidence_row() if impl == "pallas" else {}),
                # the summation tier actually benched (kernel-identity
                # relevant: reduce/stream differ at ~1e-7); null off the
                # pallas path
                "pallas_reduce": pallas_reduce,
                "pallas_preflight": preflight,
                "platform": jax.devices()[0].platform,
                "tpu_unavailable": tpu_unavailable,
                "relay_waited_s": relay_waited,
                "esdirk_points_per_sec_per_chip": esdirk_per_chip,
                # the chaos (fault-injected self-healing sweep) summary
                # (null = leg failed; its secondary line has the detail)
                "chaos": chaos_summary,
                # the elastic work-stealing fleet under churn (crash +
                # lease + torn-read; bitwise pin vs the serial engine;
                # null = leg failed — its secondary line has the detail)
                "sweep_churn": sweep_churn_summary,
                # the provenance chunk-cache A/B (warm-vs-cold emulator
                # box rebuild: speedup, hit rate, bitwise check; null =
                # leg failed — its secondary line has the detail)
                "sweep_cache": sweep_cache_summary,
                # the emulator/serving metric (null = build or measure
                # failed; the secondary line carries the full detail)
                "emulator": emulator_summary,
                # the sharded-fleet serving metric (null = leg failed or
                # no artifact; its secondary line has the full detail)
                "serve": serve_summary,
                # the self-healing fleet under a canned replica-fault
                # trace (availability / recovery / bitwise pin; null =
                # leg failed — its secondary line has the full detail)
                "chaos_serve": chaos_serve_summary,
                # the multi-tenant scenario-routed serving plane
                # (availability under chain-pool faults + forced
                # eviction, cold-admission/readmit latency, bitwise pin
                # vs single-tenant fleets; null = leg failed — its
                # secondary line has the full detail)
                "serve_multitenant": multitenant_summary,
                # the cross-host serving fabric under a whole-host kill
                # (availability with typed-loss client retries, failover
                # latency, survivor fetch-not-rebuild readmission,
                # bitwise pin vs a clean single-host fleet; null = leg
                # failed — its secondary line has the full detail)
                "serve_crosshost": crosshost_summary,
                # the closed-loop self-improving service (ROADMAP item
                # 4: traffic-drift detection → autonomous traffic-
                # steered rebuild → auto-publish rollout; hour-1 vs
                # hour-2 gated-fallback rates + the unaffected-region
                # bitwise pin; null = leg failed — its secondary line
                # has the full detail)
                "self_improve": self_improve_summary,
                # the seam-split emulator A/B (split-domain build +
                # error-gated serve trace vs single-domain; null = leg
                # failed — its secondary line has the full detail)
                "seam_split": seam_split_summary,
                "lz_sweep_points_per_sec_per_chip": lz_per_chip,
                "lz_coherent_sweep_points_per_sec_per_chip": (
                    lz_coherent_per_chip
                ),
                # the LZ scenario plane's workload legs
                # (docs/scenarios.md; null = leg failed — the secondary
                # lines carry gate residuals + vs_two_channel)
                "lz_chain_sweep_points_per_sec_per_chip": (
                    lz_chain_per_chip
                ),
                "lz_thermal_sweep_points_per_sec_per_chip": (
                    lz_thermal_per_chip
                ),
                # the in-framework O(4) bounce solver leg (potential →
                # profile throughput, vmapped vs scalar-loop A/B, gate
                # residuals; null = leg failed — the secondary line
                # carries the full detail)
                "bounce_sweep": bounce_summary,
                # the differentiable-pipeline legs (gradient throughput
                # + FD parity; NUTS-vs-stretch ESS per logp eval — null
                # = leg failed, the secondary lines carry the detail)
                "grad_sweep": grad_sweep_summary,
                "nuts_ess_per_eval": nuts_summary,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
