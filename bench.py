#!/usr/bin/env python3
"""Benchmark: parameter-sweep throughput of the TPU yields pipeline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: parameter-grid points/sec through the full flagship pipeline
(PointParams → Y_B quadrature → present-day Ω ratio) using the tabulated
KJMA fast path on a 4-D (m_χ, T_p, P, v_w) grid, batch sharded over all
local devices. Baseline: the measured reference throughput of 4.3
points/sec/core (BASELINE.md — SciPy pipeline, single CPU core), so
``vs_baseline`` is the speedup over the reference implementation.

Accuracy gate: before timing, a sample of points is checked against the
bit-reproducible NumPy reference path; the max relative error on Ω_DM/Ω_b
is reported in the JSON line and must stay ≤1e-6 (north-star contract).

Env knobs: BDLZ_BENCH_POINTS (default 262144), BDLZ_BENCH_CHUNK (default
8192 per device — sized so the (chunk × n_y) integrand temporaries fit a
single v5e chip's 16G HBM), BDLZ_BENCH_NY (default 8000),
BDLZ_BENCH_IMPL=pallas|tabulated (default: pallas on TPU — the MXU
interpolation kernel in ops/kjma_pallas.py, ~10x the tabulated XLA path,
with automatic fallback if it fails the gate — tabulated on CPU),
BDLZ_BENCH_PLATFORM=cpu to force the host platform (debug only).
"""
from __future__ import annotations

import json
import os
import sys
import time


def _axon_relay_alive() -> bool:
    """True if the axon TPU relay's compile endpoint accepts connections.

    When the relay is down, any jax backend touch with axon in the
    platform list hangs forever (observed in this environment) — so the
    bench probes the socket first and falls back to host CPU rather than
    hanging the driver.
    """
    import socket

    s = socket.socket()
    s.settimeout(2)
    try:
        s.connect(("127.0.0.1", 8083))
        return True
    except OSError:
        return False
    finally:
        s.close()


def main() -> None:
    force_cpu = os.environ.get("BDLZ_BENCH_PLATFORM") == "cpu"
    # PALLAS_AXON_POOL_IPS is what gates the sitecustomize axon-plugin
    # registration (it force-registers in every process and overrides
    # JAX_PLATFORMS), so it — not JAX_PLATFORMS — tells us whether a dead
    # relay can hang the backend.
    if not force_cpu and os.environ.get("PALLAS_AXON_POOL_IPS") and not _axon_relay_alive():
        print("[bench] axon relay unreachable; falling back to host CPU", file=sys.stderr)
        force_cpu = True
    if force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.models.yields_pipeline import point_yields, point_yields_fast
    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.parallel.mesh import batch_sharding, make_mesh
    from bdlz_tpu.parallel.sweep import build_grid, _pad_chunk
    from bdlz_tpu.physics.percolation import make_kjma_grid

    n_points = int(os.environ.get("BDLZ_BENCH_POINTS", 262144))
    n_y = int(os.environ.get("BDLZ_BENCH_NY", 8000))

    devices = jax.devices()
    n_dev = len(devices)

    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)

    # 4-D grid around the archived benchmark point (BASELINE.json configs).
    side = max(2, int(round(n_points ** 0.25)))
    axes = {
        "m_chi_GeV": np.geomspace(0.1, 10.0, side),
        "T_p_GeV": np.geomspace(30.0, 300.0, side),
        "P_chi_to_B": np.linspace(0.02, 0.9, side),
        "v_w": np.linspace(0.05, 0.9, side),
    }
    pp_all = build_grid(base, axes)
    n_total = int(np.asarray(pp_all.m_chi_GeV).shape[0])

    # Per-device chunk: the fused integrand lives as (chunk/n_dev × n_y)
    # f64 temporaries; 8192 points/device × 8000 nodes fits a 16G-HBM v5e
    # chip. Capped at the (device-rounded) grid size so large slices don't
    # pad every launch and skew the reported per-chip throughput.
    chunk = int(
        os.environ.get(
            "BDLZ_BENCH_CHUNK",
            min(8192 * n_dev, ((n_total + n_dev - 1) // n_dev) * n_dev),
        )
    )
    chunk = ((chunk + n_dev - 1) // n_dev) * n_dev

    mesh = make_mesh(shape=(n_dev, 1))
    sharding = batch_sharding(mesh)
    table = make_f_table(base.I_p, jnp)

    def make_run_chunk(impl: str):
        if impl == "pallas":
            from bdlz_tpu.ops.kjma_pallas import build_shifted_table
            from bdlz_tpu.parallel.sweep import make_sweep_step

            # make_sweep_step wraps the kernel in shard_map so each device
            # runs it on its own batch shard (pallas_call has no SPMD
            # partitioning rule of its own).
            interpret = jax.devices()[0].platform == "cpu"
            fuse = os.environ.get("BDLZ_BENCH_FUSE_EXP", "0") == "1"
            step = make_sweep_step(
                static, mesh=mesh, n_y=n_y, impl="pallas", interpret=interpret,
                fuse_exp=fuse,
            )
            aux = (table, build_shifted_table(table))
            batched = lambda ppc: step(ppc, aux).DM_over_B  # noqa: E731
        else:
            inner = jax.jit(
                jax.vmap(
                    lambda p: point_yields_fast(p, static, table, jnp, n_y=n_y).DM_over_B
                )
            )
            batched = inner

        def run_chunk(lo: int, hi: int):
            ppc = _pad_chunk(pp_all, lo, hi, chunk)
            ppc = jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), sharding), ppc)
            return batched(ppc)

        return run_chunk

    def accuracy_gate(run_chunk):
        """Max rel err of a point sample vs the NumPy reference path.

        The first chunk evaluation doubles as compile warm-up; any
        compile/runtime failure propagates to the caller for fallback.
        """
        rng = np.random.default_rng(0)
        sample = rng.choice(n_total, size=8, replace=False)
        grid_np = make_kjma_grid(np)
        max_rel = 0.0
        ratios0 = np.asarray(run_chunk(0, min(chunk, n_total)))
        for i in sample:
            pp_i = type(pp_all)(*(float(np.asarray(f)[i]) for f in pp_all))
            ref = float(point_yields(pp_i, static, grid_np, np).DM_over_B)
            lo_c = (i // chunk) * chunk
            if lo_c == 0:
                got = float(ratios0[i - lo_c])
            else:
                got = float(
                    np.asarray(run_chunk(lo_c, min(lo_c + chunk, n_total)))[i - lo_c]
                )
            if ref != 0.0:
                max_rel = max(max_rel, abs(got / ref - 1.0))
        return max_rel

    # Implementation selection: the pallas MXU-interpolation kernel is the
    # fast path on real TPU hardware; fall back to the pure-XLA tabulated
    # path if it fails to compile/run or misses the 1e-6 contract.
    default_impl = "pallas" if jax.devices()[0].platform != "cpu" else "tabulated"
    impl = os.environ.get("BDLZ_BENCH_IMPL", default_impl)
    run_chunk = None
    if impl == "pallas":
        try:
            run_chunk = make_run_chunk("pallas")
            max_rel = accuracy_gate(run_chunk)
            if max_rel > 1e-6:
                raise RuntimeError(f"pallas path rel err {max_rel:.3e} > 1e-6")
        except Exception as exc:  # noqa: BLE001 — any failure → safe path
            print(f"[bench] pallas path unavailable ({exc}); falling back", file=sys.stderr)
            impl, run_chunk = "tabulated", None
    if run_chunk is None:
        run_chunk = make_run_chunk(impl)
        max_rel = accuracy_gate(run_chunk)

    # --- timed sweep over the full grid ---
    t0 = time.time()
    done = 0
    while done < n_total:
        hi = min(done + chunk, n_total)
        out = run_chunk(done, hi)
        done = hi
    out.block_until_ready()
    seconds = time.time() - t0

    pps = n_total / seconds
    per_chip = pps / n_dev
    print(
        json.dumps(
            {
                "metric": "sweep_points_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "param-points/sec/chip (full pipeline, n_y=%d)" % n_y,
                "vs_baseline": round(per_chip / 4.3, 1),
                "n_points": n_total,
                "n_devices": n_dev,
                "seconds": round(seconds, 3),
                "rel_err_vs_reference": float(f"{max_rel:.3e}"),
                "impl": impl,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
