#!/usr/bin/env python3
"""y-grid convergence study: the truncation error behind n_y defaults.

The reference hard-codes n_y = 8000 trapezoid nodes (max(n_y, 2000),
`first_principles_yields.py:244`) with no recorded convergence evidence.
This study evaluates Y_B for the benchmark point over a ladder of n_y,
reports each level's relative distance to the finest level (Richardson-
style self-convergence), and runs the LARGEST grid through the
sp-sharded quadrature (`parallel/gridshard.py` — the intra-point
"sequence-parallel" axis) so the giant-grid path is exercised the way a
real convergence study would use it.

Output: one JSON line per n_y plus a markdown table for
docs/perf_notes.md.  Runs on whatever platform is alive (CPU fallback is
fine — the truncation error is platform-independent at f64).

Usage: python scripts/ny_convergence.py [--levels 2000,4000,8000,...]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--levels", default="2000,4000,8000,16000,32000,64000,128000",
        help="Comma list of n_y trapezoid-node counts (ascending; the "
             "finest is the self-convergence reference)",
    )
    ap.add_argument("--sp", type=int, default=2,
                    help="sp mesh axis for the giant-grid (largest-level) "
                         "sharded evaluation; 1 disables it")
    args = ap.parse_args()

    from bdlz_tpu.utils.platform import ensure_live_backend

    ensure_live_backend("ny-convergence")

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import (
        config_from_dict,
        point_params_from_config,
        static_choices_from_config,
    )
    from bdlz_tpu.models.yields_pipeline import point_yields_fast
    from bdlz_tpu.ops.kjma_table import make_f_table

    levels = sorted(int(x) for x in args.levels.split(","))
    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)
    table = make_f_table(base.I_p, jnp)
    pp = point_params_from_config(base, base.P_chi_to_B)
    pp_j = type(pp)(*(jnp.asarray(f) for f in pp))

    Y = {}
    for n_y in levels:
        Y[n_y] = float(point_yields_fast(pp_j, static, table, jnp, n_y=n_y).Y_B)

    finest = levels[-1]
    rows = []
    for n_y in levels:
        rel = abs(Y[n_y] / Y[finest] - 1.0) if n_y != finest else 0.0
        row = {"n_y": n_y, "Y_B": Y[n_y], "rel_vs_finest": float(f"{rel:.3e}")}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # giant-grid evaluation through the sp-sharded quadrature: same
    # finest-level integral, y-grid split across the mesh with one psum
    if args.sp > 1:
        from bdlz_tpu.parallel.gridshard import make_sp_quadrature
        from bdlz_tpu.parallel.mesh import make_mesh

        n_dev = len(jax.devices())
        sp = args.sp if n_dev % args.sp == 0 else 1
        if sp == 1:
            print(
                f"[ny-convergence] skipping gridshard row: {n_dev} device(s) "
                f"not divisible by --sp {args.sp} (run with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8 for the "
                "virtual mesh)",
                file=sys.stderr,
            )
        if sp > 1:
            mesh = make_mesh(shape=(n_dev // sp, sp))
            fn = make_sp_quadrature(static, mesh, n_y=finest)
            Y_sp = float(fn(pp, table))
            rel_sp = abs(Y_sp / Y[finest] - 1.0)
            row = {
                "n_y": finest, "engine": f"gridshard(sp={sp})",
                "Y_B": Y_sp, "rel_vs_single_device": float(f"{rel_sp:.3e}"),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)

    print("\n| n_y | Y_B | rel vs finest |")
    print("|---|---|---|")
    for r in rows:
        tag = f"{r['n_y']}" + (f" ({r['engine']})" if "engine" in r else "")
        rel = r.get("rel_vs_finest", r.get("rel_vs_single_device"))
        print(f"| {tag} | {r['Y_B']:.12e} | {rel:.2e} |")


if __name__ == "__main__":
    main()
