#!/usr/bin/env python3
"""Weak-scaling probe of the sweep engine's host path on a virtual CPU mesh.

Runs one sweep per device count (1, 2, 4, 8 virtual CPU devices) with a
FIXED per-device chunk, through the full production path — `run_sweep`
with chunked out_dir checkpointing, manifest hashing and host gather —
and reports total points/sec.

Interpretation on this container (ONE physical core): the n virtual
devices timeshare the core, so ideal weak scaling is *constant total
points/sec* as devices grow (same arithmetic per point, n× the work in
n× the time).  Any systematic drop with device count is erosion from the
sweep's host side: per-shard device_put, cross-device gather of chunk
outputs, manifest/chunk-file IO.  (Real multi-chip compute scaling can't
be measured here — this isolates exactly the part of the stack the chips
don't accelerate.)

One child process per device count (the backend's device count is fixed
at first JAX touch).  Usage:

    python scripts/weak_scaling.py            # full curve, prints a table
    python scripts/weak_scaling.py --devices 4  # one point (child mode)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

PER_DEVICE_POINTS = 2048
PER_DEVICE_CHUNK = 512
N_Y = 2000


def run_one(n_dev: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_dev)
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.parallel import make_mesh, run_sweep

    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    n_total = PER_DEVICE_POINTS * n_dev
    side = int(round(n_total**0.5))
    axes = {
        "m_chi_GeV": np.geomspace(0.2, 5.0, side),
        "v_w": np.linspace(0.05, 0.9, n_total // side),
    }
    static = static_choices_from_config(base)
    mesh = make_mesh(shape=(n_dev, 1))

    with tempfile.TemporaryDirectory() as out:
        # warm-up sweep (compile) on a throwaway dir, then the timed one
        run_sweep(base, axes, static, mesh=mesh,
                  chunk_size=PER_DEVICE_CHUNK * n_dev,
                  n_y=N_Y, out_dir=os.path.join(out, "warm"))
        t0 = time.time()
        res = run_sweep(base, axes, static, mesh=mesh,
                        chunk_size=PER_DEVICE_CHUNK * n_dev, n_y=N_Y,
                        out_dir=os.path.join(out, "timed"))
        dt = time.time() - t0
    n_pts = int(res.n_points)
    assert res.n_failed == 0, f"{res.n_failed} failed points"
    print(json.dumps({
        "n_devices": n_dev,
        "n_points": n_pts,
        "seconds": round(dt, 3),
        "points_per_sec_total": round(n_pts / dt, 2),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="child mode: run one device count and print JSON")
    args = ap.parse_args()
    if args.devices:
        run_one(args.devices)
        return

    rows = []
    for n in (1, 2, 4, 8):
        env = dict(os.environ, PYTHONPATH=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--devices", str(n)],
            capture_output=True, text=True, env=env, check=True,
        )
        row = json.loads(out.stdout.strip().splitlines()[-1])
        rows.append(row)
        print(json.dumps(row), flush=True)
    base_thr = rows[0]["points_per_sec_total"]
    print("\n| devices | points | seconds | total pts/s | vs 1-dev |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['n_devices']} | {r['n_points']} | {r['seconds']} "
              f"| {r['points_per_sec_total']} "
              f"| {r['points_per_sec_total'] / base_thr:.3f} |")


if __name__ == "__main__":
    main()
