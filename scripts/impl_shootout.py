#!/usr/bin/env python3
"""Timed engine comparison on the current platform: tabulated vs the
pallas kernel variants (+fuse: in-kernel Cody-Waite exp; +stream: full
integrand writeback instead of the in-kernel Kahan reduction), one JSON
line per engine plus a markdown table row for docs/perf_notes.md.

This is the evidence collector behind VERDICT r2 item #1/#2 ("a timed
pallas-vs-tabulated comparison"): same grid, same chunking, per-engine
accuracy vs the NumPy reference on a small sample, wall-clock timed after
a warm-up chunk.  Run it on the real chip:

    python scripts/impl_shootout.py [--points 65536] [--n-y 8000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=65536)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--n-y", type=int, default=8000, dest="n_y")
    ap.add_argument("--gate-points", type=int, default=64, dest="gate_points",
                    help="Audit-style adversarial population per engine "
                         "(bdlz_tpu.validation; broad/deep-MB/clip/seam) "
                         "for the per-engine accuracy column — the "
                         "fuse_exp/table-layout A/B decisions need corner "
                         "coverage, not 8 benign samples. 0 disables.")
    ap.add_argument(
        "--engines",
        default="tabulated,pallas,pallas+stream,pallas+fuse,pallas+fuse+stream",
        help="Comma list; pallas variants: +fuse (in-kernel Cody-Waite "
             "exp), +stream (write the full integrand instead of the "
             "in-kernel Kahan reduction)",
    )
    args = ap.parse_args()

    from bdlz_tpu.utils.platform import ensure_live_backend

    ensure_live_backend("shootout")

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.models.yields_pipeline import point_yields
    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.parallel.mesh import batch_sharding, make_mesh
    from bdlz_tpu.parallel.sweep import build_grid, make_chunk_runner
    from bdlz_tpu.physics.percolation import make_kjma_grid

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)
    side = max(2, int(round(args.points ** 0.25)))
    axes = {
        "m_chi_GeV": np.geomspace(0.1, 10.0, side),
        "T_p_GeV": np.geomspace(30.0, 300.0, side),
        "P_chi_to_B": np.linspace(0.02, 0.9, side),
        "v_w": np.linspace(0.05, 0.9, side),
    }
    pp_all = build_grid(base, axes)
    n_total = int(np.asarray(pp_all.m_chi_GeV).shape[0])
    chunk = ((args.chunk + n_dev - 1) // n_dev) * n_dev
    mesh = make_mesh(shape=(n_dev, 1))
    sharding = batch_sharding(mesh)
    table = make_f_table(base.I_p, jnp)
    grid_np = make_kjma_grid(np)
    from bdlz_tpu.ops.kjma_pallas import pallas_evidence_row

    # accuracy sample (shared across engines)
    rng = np.random.default_rng(0)
    sample = np.unique(rng.choice(min(chunk, n_total), size=8, replace=False))
    ref = {}
    for i in sample:
        pp_i = type(pp_all)(*(float(np.asarray(f)[i]) for f in pp_all))
        ref[int(i)] = float(point_yields(pp_i, static, grid_np, np).DM_over_B)

    # adversarial population gate (shared reference, evaluated per
    # engine through the SAME loop as bench.py — validation.py owns it)
    gate_pop = gate_ref = None
    n_gate = max(0, int(args.gate_points))
    if n_gate > 0:
        from bdlz_tpu.validation import (
            build_audit_population,
            reference_ratios_cached,
        )

        gate_pop = build_audit_population(base, n_gate, seed=1)
        gate_ref = reference_ratios_cached(gate_pop.grid, static, n_y=args.n_y)

    def population_rel(impl, fuse, reduce):
        """Max rel err of this engine over the audit population
        (raises on non-finite output — recorded as gate_error)."""
        from bdlz_tpu.validation import engine_population_max_rel

        return engine_population_max_rel(
            gate_pop.grid, gate_ref, static, mesh, sharding, table,
            impl=impl, n_y=args.n_y, fuse_exp=fuse, reduce=reduce,
        )

    rows = []
    for engine in args.engines.split(","):
        engine = engine.strip()
        impl = "pallas" if engine.startswith("pallas") else engine
        mods = engine.split("+")[1:]
        unknown = set(mods) - {"fuse", "stream"}
        if unknown:
            # a typo'd modifier must not silently record a mislabeled row
            row = {"engine": engine, "platform": platform,
                   "error": f"ValueError: unknown engine modifiers {sorted(unknown)}"}
            rows.append(row)
            print(json.dumps(row), flush=True)
            continue
        fuse = "fuse" in mods
        reduce = False if "stream" in mods else None  # None -> kernel default
        try:
            run_chunk, eff_chunk = make_chunk_runner(
                pp_all, chunk, static, mesh, sharding, table,
                impl=impl, n_y=args.n_y, fuse_exp=fuse, reduce=reduce,
            )

            first = np.asarray(run_chunk(0, min(eff_chunk, n_total)))  # warm-up
            max_rel = max(
                (abs(float(first[i]) / r - 1.0)
                 for i, r in ref.items() if i < eff_chunk),
                default=None,  # clamp shrank below every sample -> null
            )
            t0 = time.time()
            done = 0
            n_evaluated = 0  # padded chunks do full-chunk work
            while done < n_total:
                hi = min(done + eff_chunk, n_total)
                out = run_chunk(done, hi)
                done = hi
                n_evaluated += eff_chunk
            out.block_until_ready()
            dt = time.time() - t0
            row = {
                "engine": engine,
                "platform": platform,
                # throughput counts the work actually done: the last
                # chunk is padded to full size and evaluated in full
                "points_per_sec_per_chip": round(n_evaluated / dt / n_dev, 2),
                "seconds": round(dt, 3),
                "n_points": n_total,
                "n_y": args.n_y,
                "max_rel_err_vs_reference": (
                    None if max_rel is None else float(f"{max_rel:.3e}")
                ),
                # self-describing under the collector's COL_BLOCK sweep
                # (incl. its explicit 8 leg)
                **(pallas_evidence_row() if impl == "pallas" else {}),
            }
            if n_gate > 0:
                # a gate failure must not erase the timed row — stamp
                # the error beside the timing instead
                row["gate_points"] = n_gate
                try:
                    row["gate_max_rel_err"] = float(
                        "%.3e" % population_rel(impl, fuse, reduce)
                    )
                except Exception as gexc:  # noqa: BLE001
                    row["gate_error"] = f"{type(gexc).__name__}: {gexc}"
        except Exception as exc:  # noqa: BLE001 — report per-engine failure
            row = {"engine": engine, "platform": platform,
                   "error": f"{type(exc).__name__}: {exc}"}
        rows.append(row)
        print(json.dumps(row), flush=True)

    print("\n| engine | pts/s/chip | rel err | gate rel err | seconds |")
    print("|---|---|---|---|---|")
    for r in rows:
        if "error" in r:
            print(f"| {r['engine']} | FAILED: {r['error'][:60]} | — | — | — |")
        else:
            err = r["max_rel_err_vs_reference"]
            if "gate_error" in r:
                gate = f"FAILED: {r['gate_error'][:40]}"
            elif "gate_max_rel_err" in r:
                gate = format(r["gate_max_rel_err"], ".2e")
            else:
                gate = "n/a"
            print(f"| {r['engine']} | {r['points_per_sec_per_chip']} "
                  f"| {'n/a' if err is None else format(err, '.2e')} "
                  f"| {gate} | {r['seconds']} |")

    # Exit status reflects data quality so callers (the evidence
    # collector's phase gates) can distinguish "timed rows collected"
    # from "every engine failed": per-engine failures are reported in
    # the rows either way, but a run with NO timed row must not stamp a
    # collection phase as done.
    if not any("error" not in r for r in rows):
        sys.exit(1)


if __name__ == "__main__":
    main()
