#!/usr/bin/env bash
# The repo's one lint command: ruff (pycodestyle/pyflakes baseline, config
# in pyproject.toml) + bdlz-lint (the JAX-aware R1-R7 pass plus the
# whole-program knob-contract rules R8-R12 over bdlz_tpu/, see
# docs/static_analysis.md).  Exit 0 only when both passes are clean; a
# missing ruff binary downgrades the style baseline to a warning (this
# container doesn't ship it) rather than masking the bdlz-lint result.
#
# Default is the fast pre-commit path: the ANALYSIS always runs
# whole-program (the contract rules are cross-file), but findings are
# REPORTED only for git-changed files (--changed-only).  Pass --all for
# the full report — scripts/tier1.sh uses that for the PR gate.
set -u
cd "$(dirname "$0")/.."

scope="--changed-only"
if [ "${1:-}" = "--all" ]; then
    scope=""
fi

rc=0

if command -v ruff >/dev/null 2>&1; then
    echo "[lint] ruff check ."
    ruff check . || rc=1
else
    echo "[lint] ruff not installed; skipping the style baseline" \
         "(pip install ruff to enable)" >&2
fi

# the whole package tree, including the emulator + serve layers (their
# jitted query kernel / batcher hot path are prime R1/R3 surfaces —
# tests/test_lint.py additionally pins those two packages per-file) and
# the provenance package (host-side identity/store code — pinned
# per-file in test_lint.py so cache plumbing stays out of jit paths)
echo "[lint] python -m bdlz_tpu.lint bdlz_tpu/ ${scope}"
# shellcheck disable=SC2086 — $scope is deliberately word-split
python -m bdlz_tpu.lint bdlz_tpu/ ${scope} || rc=1

exit $rc
