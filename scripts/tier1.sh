#!/usr/bin/env bash
# The repo's one PR gate: the ROADMAP tier-1 test command + scripts/lint.sh,
# in that order, exiting nonzero when EITHER fails.  Every PR runs this same
# entry point so "tier-1 green" means the same thing on every machine; the
# pytest invocation below is byte-for-byte the ROADMAP.md "Tier-1 verify"
# command (update both together).  The -m 'not slow' filter is what keeps
# the real-subprocess suites (tests/test_multihost.py two-process fleets,
# tests/test_elastic_mp.py elastic worker churn, tests/test_fabric.py
# 2-process host-kill failover) out of the gate; their fast
# single-process protocol coverage (lease expiry, commit verify,
# in-process churn, fabric failover on a fake clock) runs here, and
# scripts/slow_suite.sh is the on-demand tier-2 gate that runs the
# slow-marked suites themselves.
set -u
cd "$(dirname "$0")/.."

set -o pipefail
rm -f /tmp/_t1.log
t1_budget_s=1200
t1_start=$SECONDS
timeout -k 10 "$t1_budget_s" env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly --durations=10 2>&1 | tee /tmp/_t1.log
test_rc=${PIPESTATUS[0]}
t1_wall=$((SECONDS - t1_start))
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# headroom telemetry: the suite's wall-clock against the timeout budget
# above, so a PR that eats the margin is visible BEFORE one that blows it
# — and the --durations=10 table above it names the top-10 slowest
# tests, so the next test-budget trim starts from data, not a hunch
echo "TIER1_WALL_S=${t1_wall} (budget ${t1_budget_s}s, headroom $((t1_budget_s - t1_wall))s)"

# the PR gate reports the WHOLE package (scripts/lint.sh alone defaults
# to the fast --changed-only pre-commit path)
bash scripts/lint.sh --all
lint_rc=$?

if [ "$test_rc" -ne 0 ]; then
    echo "[tier1] tests FAILED (rc=$test_rc)" >&2
    exit "$test_rc"
fi
if [ "$lint_rc" -ne 0 ]; then
    echo "[tier1] lint FAILED (rc=$lint_rc)" >&2
    exit "$lint_rc"
fi
echo "[tier1] tests + lint green"
