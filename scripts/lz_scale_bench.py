#!/usr/bin/env python3
"""Design-scale LZ ingestion + kernel benchmark (VERDICT r4 ask #7).

Real bounce-solver profiles run to millions of ξ-samples (paper §6.1/§10);
this records that the framework's full profile→P path completes with
bounded memory at that scale, and what it costs:

  1. write a ≥1e6-row profile CSV;
  2. parse it (native C++ parser; ``--numpy-compare`` adds the NumPy
     fallback's time on the same file for the speedup ratio);
  3. coherent transfer-matrix P for a speed batch over all ~1e6 segments
     (memory-bounded speed chunking, BDLZ_LZ_SPEED_CHUNK_BYTES);
  4. a coherent P(v_w) table build at ``--table-n`` nodes through the
     same chunked path (the MCMC's in-jit bridge).

Prints one JSON line per phase (peak RSS included). CPU-safe: forces the
host platform unless --tpu is passed (the kernel is pure VPU work; the
relay-outage environment makes CPU the dependable default here).
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

# runnable as `python scripts/lz_scale_bench.py` from anywhere even
# though bdlz_tpu is not pip-installed (sys.path[0] is scripts/)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_001)
    ap.add_argument("--speeds", type=int, default=64)
    ap.add_argument("--table-n", type=int, default=256)
    ap.add_argument("--numpy-compare", action="store_true",
                    help="also time the NumPy CSV fallback (slow)")
    ap.add_argument("--tpu", action="store_true",
                    help="let jax pick the accelerator (default: force CPU)")
    args = ap.parse_args()

    import numpy as np

    if not args.tpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    jax.config.update("jax_enable_x64", True)

    from bdlz_tpu.lz.profile import load_profile_csv
    from bdlz_tpu.lz.sweep_bridge import (
        make_P_of_vw_table,
        probabilities_for_points,
    )

    n = int(args.rows)
    xi = np.linspace(-300.0, 300.0, n)
    delta = -0.08 * np.tanh(xi / 4.0)
    mix = np.full(n, 0.02)

    import os as _os
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
        path = f.name
        f.write("xi,delta,m_mix\n")
        np.savetxt(f, np.column_stack([xi, delta, mix]), delimiter=",")

    # --- parse ---
    try:
        t0 = time.time()
        prof = load_profile_csv(path)
        t_native = time.time() - t0
        row = {
            "phase": "parse", "rows": n, "native_seconds": round(t_native, 3),
            "rss_mb": rss_mb(),
        }
        if args.numpy_compare:
            from bdlz_tpu.lz import profile as profile_mod

            real_read = profile_mod._read_csv

            def numpy_read(p):
                data = np.genfromtxt(p, delimiter=",", names=True, dtype=float)
                names = list(data.dtype.names)
                return names, np.column_stack([data[c] for c in names])

            profile_mod._read_csv = numpy_read
            try:
                t0 = time.time()
                prof_np = profile_mod.load_profile_csv(path)
                t_numpy = time.time() - t0
            finally:
                profile_mod._read_csv = real_read
            np.testing.assert_allclose(prof_np.xi, prof.xi, rtol=1e-15)
            row["numpy_seconds"] = round(t_numpy, 3)
            row["native_speedup"] = round(t_numpy / t_native, 1)
    finally:
        _os.unlink(path)  # ~70 MB per run — don't accumulate in /tmp
    print(json.dumps(row), flush=True)

    # --- coherent kernel over the full profile ---
    v = np.linspace(0.05, 0.9, int(args.speeds))
    t0 = time.time()
    P = probabilities_for_points(prof, v, method="coherent")
    t_coh = time.time() - t0
    print(json.dumps({
        "phase": "coherent", "segments": n - 1, "speeds": len(v),
        "seconds": round(t_coh, 2),
        "speeds_per_sec": round(len(v) / t_coh, 2),
        "finite": bool(np.isfinite(P).all()),
        "P_range": [float(P.min()), float(P.max())],
        "rss_mb": rss_mb(),
    }), flush=True)

    # --- P(v_w) table build (the MCMC bridge) ---
    t0 = time.time()
    table = make_P_of_vw_table(prof, "coherent", 0.05, 0.9, n=args.table_n)
    t_tab = time.time() - t0
    vals = np.asarray(table.values)
    print(json.dumps({
        "phase": "ptable", "segments": n - 1, "nodes": int(args.table_n),
        "seconds": round(t_tab, 2),
        "finite": bool(np.isfinite(vals).all()),
        "rss_mb": rss_mb(),
    }), flush=True)


if __name__ == "__main__":
    sys.exit(main())
