#!/usr/bin/env bash
# Tier-2 on-demand gate: every @pytest.mark.slow suite — the real-
# subprocess / wall-clock tests tier-1 excludes via -m 'not slow'
# (scripts/tier1.sh).  Run it before merging changes that touch the
# cross-process protocols it covers:
#
#   tests/test_multihost.py   two-process jax.distributed fleets
#   tests/test_elastic_mp.py  external elastic-worker churn (sweep_cli)
#   tests/test_provenance.py  registry fetch-vs-evict race
#   tests/test_fabric.py      2-process whole-host failover
#   tests/test_bench.py       bench harness smoke + leg-cache replay
#   ... plus any other slow-marked test pytest collects.
#
# Same interpreter pins as tier-1 so "slow green" means the same thing
# on every machine.  Extra args pass through to pytest (e.g.
# scripts/slow_suite.sh tests/test_fabric.py to run one suite).
set -u
cd "$(dirname "$0")/.."

slow_budget_s=2400
exec timeout -k 10 "$slow_budget_s" env JAX_PLATFORMS=cpu \
    python -m pytest "${@:-tests/}" -q -m slow \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly --durations=10
