#!/usr/bin/env python3
"""Grid-wide 1e-6 accuracy proof + CPU→TPU error attribution.

VERDICT r2 weak #4: the bench gate samples ~13 points of a 279,841-point
grid, and the recorded TPU rel-err (2.557e-09) sits ~3 decades above the
CPU path's (3.498e-12) with no artifact saying where the drift comes
from.  This audit closes both:

1. **Proof**: ≥1024 randomized configs spanning both n_eq branches
   (relativistic and Maxwell–Boltzmann), the y-support clip edges
   (T windows pushed against y = −80/+50), and the T = m/3 seam
   (configs whose seam falls inside the quadrature window), evaluated on
   the CURRENT platform's JAX path (tabulated engine, plus pallas when
   it preflights) against the bit-reproducible NumPy reference path.
   Writes max/percentile rel-err to the artifact JSON.

2. **Attribution**: for the worst points, per-stage comparison of the
   JAX path vs NumPy — F-table values, thermo/window prefactor stream,
   and the final trapezoid-summed Y_B — so the artifact names the op
   where f64 emulation loses the decades, not just the total.

Usage: python scripts/accuracy_audit.py [--points 1024] [--out FILE]
(run on the TPU for the real artifact; on CPU it certifies the JAX-CPU
path instead). The artifact lands at ACCURACY_AUDIT.json by default.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python scripts/accuracy_audit.py` from the repo root even
# though bdlz_tpu is not pip-installed (sys.path[0] is scripts/)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=1024)
    ap.add_argument("--out", default="ACCURACY_AUDIT.json")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n-y", type=int, default=8000, dest="n_y")
    args = ap.parse_args()

    from bdlz_tpu.utils.platform import ensure_live_backend

    ensure_live_backend("audit")

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.models.yields_pipeline import point_yields_fast
    from bdlz_tpu.ops.kjma_table import eval_f_table, make_f_table

    platform = jax.devices()[0].platform
    n = int(args.points)

    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)

    # Shared population builder (bdlz_tpu.validation): the bench's
    # on-hardware gate draws from the same design, so this artifact and
    # the benched-engine gate cannot drift apart.
    from bdlz_tpu.validation import (
        build_audit_population,
        reference_ratios_cached,
    )

    pop = build_audit_population(base, n, seed=args.seed)
    grid = pop.grid
    m, T_p = pop.axes["m_chi_GeV"], pop.axes["T_p_GeV"]
    sigma_y, beta = pop.axes["source_shape_sigma_y"], pop.axes["beta_over_H"]
    T_min, T_max = pop.axes["T_min_over_Tp"], pop.axes["T_max_over_Tp"]

    # --- reference: the bit-reproducible NumPy path ---------------------
    t0 = time.time()
    # n_y aligned with the JAX leg: the artifact must measure backend
    # error at equal discretization, not y-grid truncation
    ref_stats = {}
    ref = reference_ratios_cached(grid, static, n_y=args.n_y,
                                  stats=ref_stats)
    t_ref = time.time() - t0

    # --- JAX path (tabulated engine, the bench's fallback/default) ------
    table = make_f_table(base.I_p, jnp)
    grid_j = jax.tree.map(jnp.asarray, grid)
    got = np.asarray(
        jax.jit(
            jax.vmap(
                lambda p: point_yields_fast(p, static, table, jnp, n_y=args.n_y).DM_over_B
            )
        )(grid_j)
    )

    rel = np.abs(got / ref - 1.0)
    order = np.argsort(rel)[::-1]

    def pct(q):
        return float(np.percentile(rel, q))

    report = {
        "platform": platform,
        "n_points": n,
        "n_y": args.n_y,
        "engine": "tabulated",
        "max_rel_err": float(rel.max()),
        "p99_rel_err": pct(99),
        "p90_rel_err": pct(90),
        "median_rel_err": pct(50),
        "contract_1e-6_ok": bool(rel.max() <= 1e-6),
        "population": dict(pop.counts),
        "worst_points": [
            {
                "rel_err": float(rel[i]),
                "m_chi_GeV": float(m[i]),
                "T_p_GeV": float(T_p[i]),
                "sigma_y": float(sigma_y[i]),
                "beta_over_H": float(beta[i]),
                "window": [float(T_min[i]), float(T_max[i])],
            }
            for i in order[:5]
        ],
        "reference_seconds": round(t_ref, 1),
        # a warm cache makes reference_seconds a disk read, not the
        # scalar-loop cost — stamp which one this artifact recorded
        "reference_cached": bool(ref_stats.get("cache_hit")),
    }

    # --- pallas engine too, when it can run here ------------------------
    if platform != "cpu":
        from bdlz_tpu.ops.kjma_pallas import (
            build_shifted_table,
            pallas_preflight,
            point_yields_pallas,
        )

        ok, _, detail = pallas_preflight(n_y=args.n_y)
        report["pallas_preflight"] = f"{'PASS' if ok else 'FAIL'}: {detail}"
        if ok:
            t4 = build_shifted_table(table)
            got_p = np.asarray(
                point_yields_pallas(grid_j, static, table, t4, n_y=args.n_y).DM_over_B
            )
            rel_p = np.abs(got_p / ref - 1.0)
            report["pallas"] = {
                "max_rel_err": float(rel_p.max()),
                "p99_rel_err": float(np.percentile(rel_p, 99)),
                "median_rel_err": float(np.percentile(rel_p, 50)),
                "contract_1e-6_ok": bool(rel_p.max() <= 1e-6),
            }

    # --- attribution: stage-wise JAX-vs-NumPy on the worst points -------
    # Stages: (a) the F(y) table VALUES (the big (n×1200) tensor build —
    # f64 exp/trapezoid on this platform), (b) table INTERPOLATION at the
    # worst point's query nodes, (c) the per-node integrand prefactor
    # stream (thermo/window/Jacobian — f64 exp/sqrt), (d) the final
    # trapezoid sum. Each compares this platform's f64 against NumPy.
    table_np = make_f_table(base.I_p, np)

    def rel_to_scale(a, b):
        """max |a-b| relative to b, guarding exact-zero tails (F(y)
        underflows to 0 identically on both paths near y = +50)."""
        a, b = np.asarray(a), np.asarray(b)
        denom = np.maximum(np.abs(b), np.max(np.abs(b)) * 1e-12 + 1e-300)
        return float(np.max(np.abs(a - b) / denom))

    stage = {}
    stage["f_table_values"] = rel_to_scale(table.values, table_np.values)
    iw = int(order[0])
    pp_w = type(grid)(*(float(np.asarray(f)[iw]) for f in grid))
    ys = np.linspace(-49.0, 49.0, 4001)
    interp_j = np.asarray(eval_f_table(jnp.asarray(ys), table, jnp))
    # isolate interpolation arithmetic from table-build differences by
    # querying the NumPy interpolator on the SAME (JAX-built) values
    table_mixed = type(table_np)(
        y0=float(table_np.y0), inv_dy=float(table_np.inv_dy),
        values=np.asarray(table.values), I_p=table_np.I_p,
    )
    interp_np = eval_f_table(ys, table_mixed, np)
    stage["f_table_interp"] = rel_to_scale(interp_j, interp_np)

    from bdlz_tpu.solvers.quadrature import integrand_stream_probe

    probe = integrand_stream_probe(pp_w, static, table, jnp, n_y=args.n_y)
    probe_np = integrand_stream_probe(pp_w, static, table_np, np, n_y=args.n_y)
    for k in probe:
        stage[k] = rel_to_scale(probe[k], probe_np[k])
    report["stage_attribution_worst_point"] = stage

    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items()
                      if k not in ("worst_points",)}))
    print(f"[audit] artifact written to {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
