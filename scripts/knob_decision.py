#!/usr/bin/env python3
"""Turn collected hardware A/B rows into ONE production kernel config.

VERDICT r4 ask #2: the pallas kernel grew three knobs (fuse_exp, the
bf16x3 masked-split table, COL_BLOCK) plus the reduce/stream tier without
a single hardware data point.  This script reads the evidence collector's
log (`scripts/collect_tpu_evidence.sh` >> /tmp/evidence_r5.log), pulls
every shootout/bench JSON row, and prints:

  1. the full measured variant table (throughput + gate error), and
  2. the recommended defaults — fastest variant whose adversarial gate
     error stays ≤ 1e-6 — as concrete `ops/kjma_pallas.py` constants and
     a ready-to-paste perf_notes decision table.

Rows are matched on TPU platform only (CPU/interpret rows are listed but
never drive a decision).
"""
from __future__ import annotations

import argparse
import json
import sys


def parse_rows(path: str):
    rows = []
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and ("engine" in d or "metric" in d):
                rows.append(d)
    return rows


def variant_key(r) -> str:
    k = r.get("engine", r.get("impl", "?"))
    if r.get("pallas_col_block") is not None:
        k += f" cb={r['pallas_col_block']}"
    if r.get("pallas_table_split3"):
        k += " bf16x3"
    return k


def gate_err(r):
    for key in ("gate_max_rel_err", "max_rel_err_vs_reference",
                "rel_err_vs_reference"):
        if r.get(key) is not None:
            return float(r[key])
    return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="/tmp/evidence_r5.log")
    ap.add_argument("--contract", type=float, default=1e-6)
    args = ap.parse_args()

    rows = parse_rows(args.log)
    engine_rows = [r for r in rows if "engine" in r and "error" not in r]
    tpu_rows = [r for r in engine_rows if r.get("platform") == "tpu"]
    failed = [r for r in rows if "engine" in r and "error" in r]

    print(f"# parsed {len(rows)} JSON rows from {args.log}: "
          f"{len(engine_rows)} timed ({len(tpu_rows)} on tpu), "
          f"{len(failed)} failed\n")

    if engine_rows:
        print("| variant | platform | pts/s/chip | gate rel err |")
        print("|---|---|---|---|")
        for r in sorted(engine_rows,
                        key=lambda r: -(r.get("points_per_sec_per_chip") or 0)):
            e = gate_err(r)
            print(f"| {variant_key(r)} | {r.get('platform')} "
                  f"| {r.get('points_per_sec_per_chip')} "
                  f"| {'n/a' if e is None else format(e, '.2e')} |")
        print()
    for r in failed:
        print(f"# FAILED {variant_key(r)}: {r['error'][:100]}")

    # decisions require the ADVERSARIAL population gate specifically: a
    # row whose gate failed (gate_error) or never ran must not be crowned
    # via the weak in-grid spot sample's number
    candidates = [
        r for r in tpu_rows
        if r.get("engine", "").startswith("pallas")
        and "gate_error" not in r
        and r.get("gate_max_rel_err") is not None
        and float(r["gate_max_rel_err"]) <= args.contract
        and r.get("points_per_sec_per_chip")
    ]
    baseline = [r for r in tpu_rows if r.get("engine") == "tabulated"]
    if not candidates:
        print("\n# NO tpu pallas row passes the contract yet — no "
              "decision possible (is the collector done?)")
        sys.exit(1)

    best = max(candidates, key=lambda r: r["points_per_sec_per_chip"])
    mods = set(best.get("engine", "").split("+")[1:])
    print("\n## Recommended production kernel configuration\n")
    print(f"winner: {variant_key(best)} at "
          f"{best['points_per_sec_per_chip']} pts/s/chip "
          f"(gate {gate_err(best):.2e})")
    if baseline:
        base_best = max(baseline, key=lambda r: r["points_per_sec_per_chip"])
        ratio = best["points_per_sec_per_chip"] / base_best["points_per_sec_per_chip"]
        print(f"vs tabulated {base_best['points_per_sec_per_chip']} "
              f"pts/s/chip -> {ratio:.2f}x")
    print("\nFlip these defaults in ops/kjma_pallas.py (then demote the "
          "losing variants from the resume-identity surface):")
    print(f"  REDUCE_DEFAULT   = {'stream' not in mods}")
    print(f"  FUSE_EXP default = {'fuse' in mods}")
    print(f"  TABLE_SPLIT3     = {bool(best.get('pallas_table_split3'))}")
    print(f"  COL_BLOCK_DEFAULT= {best.get('pallas_col_block', 8)}")


if __name__ == "__main__":
    main()
