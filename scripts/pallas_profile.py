#!/usr/bin/env python3
"""Attribute pallas-path runtime: kernel vs prep vs fallback, on hardware.

Three timed stages on identical shapes (one chunk, production n_y):

* ``kernel-only`` — the bare `pallas_call` on pre-staged device tiles
  (realistic index/fraction distributions), both reduce tiers.  This is
  the MXU one-hot interpolation in isolation: its throughput bounds what
  any prep optimization could unlock.
* ``end-to-end`` — `integrate_YB_pallas` on a real parameter chunk (the
  f64 stream prep + kernel + f64 trapezoid reconstruction).
* ``tabulated`` — the pure-XLA gather path on the same chunk (the
  engine the kernel exists to beat; ~90% gather per r2 measurements).

``end-to-end − kernel-only`` ≈ the emulated-f64 prep + reduction cost:
if that dominates, round-4 effort goes to double-float in-kernel prep;
if kernel-only dominates, it goes to cutting the one-hot matmul work
(e.g. dynamic row-slicing — nodes of one 128-lane column span only ~3-4
table rows at production shapes).

Usage: python scripts/pallas_profile.py [--points 8192] [--n-y 8000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=8192)
    ap.add_argument("--n-y", type=int, default=8000, dest="n_y")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    from bdlz_tpu.utils.platform import ensure_live_backend

    ensure_live_backend("pallas-profile")

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bdlz_tpu.config import config_from_dict, static_choices_from_config
    from bdlz_tpu.models.yields_pipeline import point_yields_fast
    from bdlz_tpu.ops.kjma_pallas import (
        COL_BLOCK,
        ROWS,
        build_shifted_table,
        integrate_YB_pallas,
        interp_multiply,
        interp_multiply_fused,
        split_f64,
    )
    from bdlz_tpu.ops.kjma_table import make_f_table
    from bdlz_tpu.parallel.sweep import build_grid

    platform = jax.devices()[0].platform
    interpret = platform == "cpu"
    if interpret:
        print("[profile] WARNING: CPU interpret mode — timings are NOT "
              "hardware numbers", file=sys.stderr)

    # same device-memory clamp as bench.py/impl_shootout — an OOM'd
    # compile can destabilize the accelerator relay
    from bdlz_tpu.parallel.sweep import _clamp_chunk_to_memory

    P = _clamp_chunk_to_memory(int(args.points), int(args.n_y), None, "pallas")
    if P != int(args.points):
        print(f"[profile] --points clamped to {P}", file=sys.stderr)
    n_y = int(args.n_y)
    ncol = -(-n_y // (ROWS * COL_BLOCK)) * COL_BLOCK

    base = config_from_dict(
        {
            "regime": "nonthermal",
            "P_chi_to_B": 0.14925839040304145,
            "source_shape_sigma_y": 9.0,
            "incident_flux_scale": 1.07e-9,
            "Y_chi_init": 4.90e-10,
        }
    )
    static = static_choices_from_config(base)
    table = make_f_table(base.I_p, jnp)
    t4 = build_shifted_table(table)
    rng = np.random.default_rng(0)
    grid = build_grid(
        base,
        {
            "m_chi_GeV": rng.uniform(0.1, 5.0, P),
            "T_p_GeV": rng.uniform(30.0, 300.0, P),
            "v_w": rng.uniform(0.05, 0.95, P),
        },
        product=False,
    )
    grid = jax.tree.map(jnp.asarray, grid)

    def timed(fn, *xs):
        # compile + warm-up, BLOCKED — async dispatch would otherwise let
        # the warm-up tail bleed into the first measured repeat
        jax.tree.map(
            lambda a: a.block_until_ready() if hasattr(a, "block_until_ready") else a,
            fn(*xs),
        )
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.time()
            out = fn(*xs)
            jax.tree.map(
                lambda a: a.block_until_ready() if hasattr(a, "block_until_ready") else a,
                out,
            )
            best = min(best, time.time() - t0)
        return best

    rows = []

    from bdlz_tpu.ops.kjma_pallas import pallas_evidence_row

    def report(name, seconds):
        row = {"stage": name, "seconds": round(seconds, 4),
               "points_per_sec": round(P / seconds, 1), "platform": platform,
               # label kernel-variant legs (the collector's split3 /
               # COL_BLOCK phases) so rows are attributable without
               # parsing the surrounding log banners
               **pallas_evidence_row()}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # --- kernel-only on pre-staged tiles (realistic distributions) ---
    n_tab = int(np.asarray(table.values).shape[0])
    ghat = jnp.asarray(
        rng.uniform(0.0, 1.0, (P, ncol, ROWS)).astype(np.float32)
    )
    i1 = jnp.asarray(
        rng.integers(1, n_tab - 3, (P, ncol, ROWS)).astype(np.int32)
    )
    sfrac = jnp.asarray(rng.uniform(0.0, 1.0, (P, ncol, ROWS)).astype(np.float32))
    a = jnp.asarray(rng.uniform(-60.0, 0.0, (P, ncol, ROWS)))
    a_hi, a_lo = split_f64(a)

    kern_red = jax.jit(lambda g, i, s: interp_multiply(
        g, i, s, t4, interpret=interpret, reduce=True))
    report("kernel-only reduce", timed(kern_red, ghat, i1, sfrac))
    kern_str = jax.jit(lambda g, i, s: interp_multiply(
        g, i, s, t4, interpret=interpret, reduce=False))
    report("kernel-only stream", timed(kern_str, ghat, i1, sfrac))
    kern_fus = jax.jit(lambda g, ah, al, i, s: interp_multiply_fused(
        g, ah, al, i, s, t4, interpret=interpret, reduce=True))
    report("kernel-only fused+reduce",
           timed(kern_fus, ghat, a_hi, a_lo, i1, sfrac))

    # --- end-to-end pallas (prep + kernel + reconstruction) ---
    for fuse in (False, True):
        e2e = jax.jit(lambda g, f=fuse: integrate_YB_pallas(
            g, static.chi_stats, table, t4, n_y=n_y,
            interpret=interpret, fuse_exp=f, reduce=True))
        report(f"end-to-end pallas fuse={fuse}", timed(e2e, grid))

    # --- the XLA tabulated fallback on the same chunk ---
    tab_fn = jax.jit(jax.vmap(
        lambda p: point_yields_fast(p, static, table, jnp, n_y=n_y).Y_B))
    report("tabulated (XLA gather)", timed(tab_fn, grid))

    print("\n| stage | seconds | pts/s |")
    print("|---|---|---|")
    for r in rows:
        print(f"| {r['stage']} | {r['seconds']} | {r['points_per_sec']} |")


if __name__ == "__main__":
    main()
