#!/bin/bash
# Relay-recovery evidence collector (VERDICT r3 "Next round" items 1-5).
#
# Waits for the axon TPU relay, then collects — phase by phase, each
# stamped in evidence/stamps/ so a mid-collection relay death resumes at
# the next incomplete phase on the next invocation:
#
#   1. pallas preflight, grown incrementally (2048 -> 8192; heavy first
#      compiles have killed the relay before — docs/perf_notes.md
#      "Memory limits")
#   2. impl shootout: tabulated vs pallas variants incl. the fuse_exp
#      A/B (VERDICT items 1 and 4); later phases sweep COL_BLOCK and
#      the bf16x3 masked-split table (pallas_evidence_row labels rows)
#   3. accuracy audit on the chip, 1024 configs (VERDICT item 2)
#   4. pallas profile: kernel vs prep vs gather attribution (item 8)
#   5. full bench.py — sweep + ESDIRK + LZ-sweep metrics on TPU (items
#      1 and 3); output preserved at evidence/BENCH_tpu.jsonl (one JSON
#      doc per line, secondary metric lines first — the MAIN metric is
#      always the LAST line, same contract the driver uses)
#
# Logs to stdout (launcher redirects, e.g. >> /tmp/evidence.log).
# Artifacts: /root/repo/evidence/ + ACCURACY_AUDIT.json
set -u
cd /root/repo
mkdir -p evidence/stamps

phase() {  # phase <name> <timeout-s> <cmd...>
  local name="$1" tmo="$2"; shift 2
  if [ -f "evidence/stamps/$name" ]; then
    echo "=== phase $name: already done, skipping ==="
    return 0
  fi
  if past_deadline; then
    # never START chip work past the activity budget — the driver's
    # end-of-round bench owns the chip then (checked per phase, not
    # just per attempt: one attempt chains hours of phases)
    echo "=== phase $name: past activity budget, not starting ==="
    return 1
  fi
  echo "=== phase $name: start $(date -u +%H:%M:%S) ==="
  if timeout "$tmo" "$@"; then
    touch "evidence/stamps/$name"
    echo "=== phase $name: OK $(date -u +%H:%M:%S) ==="
    return 0
  else
    echo "=== phase $name: FAILED/TIMEOUT rc=$? $(date -u +%H:%M:%S) ==="
    return 1
  fi
}

wait_relay() {
  python - <<'EOF'
from bdlz_tpu.utils.platform import wait_for_relay
import sys
sys.exit(0 if wait_for_relay(max_wait_s=float(36000), poll_s=30.0) else 1)
EOF
}

echo "=== collector started $(date -u) ==="
# Stop starting chip work near the round's end: the driver's own bench
# runs on the single chip then, and concurrent heavy compiles are the
# suspected relay killer (docs/perf_notes.md "Memory limits").
START_S=$(date +%s)
BUDGET_S=${BDLZ_COLLECT_BUDGET_S:-30600}   # default 8.5h of activity
past_deadline() { [ $(( $(date +%s) - START_S )) -gt "$BUDGET_S" ]; }

for attempt in 1 2 3 4 5; do
  if past_deadline; then
    echo "=== activity budget exhausted before recovery; exiting to keep "
    echo "    the chip free for the driver's end-of-round bench ==="
    exit 1
  fi
  echo "=== waiting for relay (attempt $attempt) ==="
  wait_relay || { echo "RELAY NEVER RECOVERED"; exit 1; }
  echo "=== relay alive $(date -u) ==="
  if past_deadline; then
    echo "=== relay recovered past the activity budget; leaving the chip "
    echo "    to the driver's bench ==="
    exit 1
  fi

  phase preflight 1200 python - <<'EOF' || continue
import time
import jax
jax.config.update("jax_enable_x64", True)
from bdlz_tpu.ops.kjma_pallas import pallas_preflight
for n_y, fuse in [(2048, False), (8192, False), (8192, True)]:
    t0 = time.time()
    ok, rel, detail = pallas_preflight(n_y=n_y, fuse_exp=fuse)
    print(f"preflight n_y={n_y} fuse={fuse}: ok={ok} rel={rel} "
          f"{detail} {time.time()-t0:.1f}s", flush=True)
EOF

  phase shootout 2400 python scripts/impl_shootout.py --points 16384 --n-y 8000 \
      || continue
  phase audit 3600 python scripts/accuracy_audit.py --points 1024 || continue
  phase profile 1800 python scripts/pallas_profile.py --points 8192 || continue
  phase profile-split3 1800 env BDLZ_PALLAS_TABLE_SPLIT3=1 \
      python scripts/pallas_profile.py --points 8192 || continue
  phase colblock 2400 bash -c '
    any_ok=0
    for cb in 8 16 32; do
      echo "--- COL_BLOCK=$cb ---"
      if BDLZ_PALLAS_COL_BLOCK=$cb timeout 700 python scripts/impl_shootout.py \
          --points 8192 --n-y 8000 --engines pallas; then
        any_ok=1
      else
        echo "COL_BLOCK=$cb: failed/timeout"
      fi
    done
    [ "$any_ok" = 1 ]' || continue
  phase tableprec 1500 bash -c '
    echo "--- bf16x3 masked-split table (BDLZ_PALLAS_TABLE_SPLIT3=1) ---"
    BDLZ_PALLAS_TABLE_SPLIT3=1 timeout 700 python scripts/impl_shootout.py \
      --points 8192 --n-y 8000 --engines pallas,pallas+fuse' || continue
  phase bench 3600 bash -c \
      'set -o pipefail; python bench.py | tee evidence/BENCH_tpu.jsonl' \
      || continue
  echo "=== ALL PHASES DONE $(date -u) ==="
  exit 0
done
echo "=== collector exhausted attempts $(date -u) ==="
exit 1
