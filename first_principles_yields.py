#!/usr/bin/env python3
"""Reference-compatible entry point.

The archived reproduction command (reference run.txt:1) is

    python first_principles_yields.py --config yields_config_equal_mass.json --diagnostics

This shim forwards to the framework CLI (`bdlz_tpu.cli`), whose NumPy
backend reproduces the archived golden outputs byte-for-byte; add
``"backend": "tpu"`` to the config (or pass ``--backend tpu``) for the
jitted TPU path.
"""
from bdlz_tpu.cli import main

if __name__ == "__main__":
    main()
