// bdlz_io — native IO runtime for the bdlz_tpu framework.
//
// Fast bounce-profile CSV ingestion for the Landau–Zener kernel. Wall
// profiles from bounce solvers can run to millions of rows; NumPy's
// genfromtxt parses them ~6x slower than this streaming parser (measured
// at 1e6 rows: 0.88 s vs 5.1 s — scripts/lz_scale_bench.py). Exposed
// through ctypes (no pybind11 in this environment) with a two-call
// protocol that keeps all allocation on the Python side:
//
//   1. bdlz_csv_dims(path, &rows, &cols, header_buf, cap)  -> probe
//   2. bdlz_csv_fill(path, out /* rows*cols doubles */, rows, cols)
//
// Returns 0 on success, negative error codes otherwise. Rows with the
// wrong column count abort the parse (error -3) rather than silently
// skipping data. Parsing uses strtod, so any standard float format works.

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr long kMaxLine = 1 << 16;

struct LineReader {
  FILE* f;
  std::vector<char> buf;
  explicit LineReader(const char* path) : f(std::fopen(path, "rb")), buf(kMaxLine) {}
  ~LineReader() {
    if (f) std::fclose(f);
  }
  bool ok() const { return f != nullptr; }
  // Returns pointer to a NUL-terminated line without trailing newline, or
  // nullptr at EOF.
  char* next() {
    if (!std::fgets(buf.data(), kMaxLine, f)) return nullptr;
    size_t n = std::strlen(buf.data());
    while (n > 0 && (buf[n - 1] == '\n' || buf[n - 1] == '\r')) buf[--n] = '\0';
    return buf.data();
  }
};

int count_cols(const char* line) {
  int cols = 1;
  for (const char* p = line; *p; ++p)
    if (*p == ',') ++cols;
  return cols;
}

bool is_blank(const char* line) {
  for (const char* p = line; *p; ++p)
    if (!std::isspace(static_cast<unsigned char>(*p))) return false;
  return true;
}

}  // namespace

extern "C" {

// Probe dimensions and copy the (comma-joined) header into header_buf.
// Errors: -1 open failed, -2 empty file / no header, -4 header too long.
int bdlz_csv_dims(const char* path, long* rows, int* cols, char* header_buf,
                  int header_cap) {
  LineReader r(path);
  if (!r.ok()) return -1;
  char* header = r.next();
  if (!header || is_blank(header)) return -2;
  if (static_cast<int>(std::strlen(header)) >= header_cap) return -4;
  std::strncpy(header_buf, header, header_cap);
  *cols = count_cols(header);
  long n = 0;
  while (char* line = r.next())
    if (!is_blank(line)) ++n;
  *rows = n;
  return 0;
}

// Fill out[rows*cols] row-major. Errors: -1 open failed, -2 no header,
// -3 malformed row (wrong column count or non-numeric cell), -5 row
// count changed between probe and fill.
int bdlz_csv_fill(const char* path, double* out, long rows, int cols) {
  LineReader r(path);
  if (!r.ok()) return -1;
  if (!r.next()) return -2;  // skip header
  long i = 0;
  while (char* line = r.next()) {
    if (is_blank(line)) continue;
    if (i >= rows) return -5;
    char* p = line;
    for (int c = 0; c < cols; ++c) {
      char* end = nullptr;
      out[i * cols + c] = std::strtod(p, &end);
      if (end == p) return -3;
      p = end;
      while (*p == ' ' || *p == '\t') ++p;
      if (c < cols - 1) {
        if (*p != ',') return -3;
        ++p;
      }
    }
    if (*p != '\0' && !is_blank(p)) return -3;
    ++i;
  }
  return i == rows ? 0 : -5;
}

}  // extern "C"
